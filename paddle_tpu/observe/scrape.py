"""fluid-horizon observatory: one scraper, one store, one pane of glass.

Every fleet process exposes a pulse `/metrics` endpoint, but each is a
POINT-IN-TIME view of ONE process: "what is the fleet's QPS" or "is any
pserver's replication lag growing" requires polling N endpoints over
time and joining the answers. This module is that join:

- `Scraper` polls every registered target's `/metrics` on an interval
  (one daemon thread, stdlib urllib — a dead target scores `up=0` and
  never stalls the loop past its timeout) and ingests the samples into
- `TimeSeriesStore` — a bounded in-memory store of labeled series
  (per-sample deques; every point carries the scrape wall-time), with
  the three query shapes a control loop needs:

      rate(name, window_s)          counter increase/sec, reset-aware,
                                    summed across matching series
      latest(name, agg=...)         newest gauge value per series
                                    (sum/max/min across, or the list)
      percentile(name, q, window_s) histogram_quantile over the
                                    windowed increase of the _bucket
                                    series — the classic Prometheus
                                    estimator, cross-instance
      mean(name, window_s)          windowed Δ_sum/Δ_count of a
                                    histogram (e.g. decode occupancy)

- `fleet_overview()` derives the fleet-level series ROADMAP's
  fluid-tide controller needs — total QPS, max replication lag, decode
  occupancy, request p99 — from whatever targets are being scraped.

Labels: every ingested sample gains `job` (the target's role name) and
`instance` (host:port) so per-process series never collide and queries
can filter either way. The store is bounded in BOTH axes (points per
series, series count) — a scraper left running for a week cannot grow
host memory past its budget.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import metrics as _metrics

DEFAULT_POINTS = 600        # per series: 10 min of 1 s scrapes
DEFAULT_MAX_SERIES = 8192

#: synthetic per-target liveness series (1 scraped ok, 0 failed)
UP_SERIES = "horizon_up"


def _label_key(labels: Dict[str, str]) -> Tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _matches(labels: Dict[str, str], match: Optional[Dict[str, str]]) -> bool:
    if not match:
        return True
    return all(labels.get(k) == str(v) for k, v in match.items())


class TimeSeriesStore:
    """Bounded labeled time series: (name, labels) -> deque[(ts, value)].

    Writers are scrape threads, readers are CLI/controller threads; every
    access to the two maps below holds `_lock` (appends and queries are
    O(points) at worst — never network- or disk-bound), so the store
    needs no finer discipline.
    """

    def __init__(self, max_points: int = DEFAULT_POINTS,
                 max_series: int = DEFAULT_MAX_SERIES):
        self.max_points = int(max_points)
        self.max_series = int(max_series)
        self._lock = threading.Lock()
        # series data: (name, label_key) -> deque[(ts, value)]
        self._series: Dict[Tuple, deque] = {}   # guarded_by: self._lock
        # (name, label_key) -> labels dict (for query results)
        self._labels: Dict[Tuple, Dict[str, str]] = {}  # guarded_by: self._lock
        self._dropped = 0                       # guarded_by: self._lock

    def add(self, name: str, labels: Dict[str, str], value: float,
            ts: Optional[float] = None):
        key = (name, _label_key(labels))
        ts = time.time() if ts is None else ts
        with self._lock:
            dq = self._series.get(key)
            if dq is None:
                if len(self._series) >= self.max_series:
                    self._dropped += 1   # bounded: new series are shed
                    return
                dq = self._series[key] = deque(maxlen=self.max_points)
                self._labels[key] = dict(labels)
            dq.append((ts, float(value)))

    # -- introspection -----------------------------------------------------

    def names(self) -> List[str]:
        with self._lock:
            return sorted({name for name, _ in self._series})

    def series(self, name: str, match: Optional[dict] = None
               ) -> List[Tuple[Dict[str, str], List[Tuple[float, float]]]]:
        """[(labels, [(ts, value), ...]), ...] for every matching series."""
        with self._lock:
            out = []
            for (n, lk), dq in self._series.items():
                if n != name:
                    continue
                labels = self._labels[(n, lk)]
                if _matches(labels, match):
                    out.append((dict(labels), list(dq)))
        return out

    def dropped_series(self) -> int:
        with self._lock:
            return self._dropped

    def __len__(self):
        with self._lock:
            return len(self._series)

    # -- queries -----------------------------------------------------------

    def latest(self, name: str, match: Optional[dict] = None,
               agg: Optional[str] = None):
        """Newest value per matching series. `agg` folds across series
        ("sum"/"max"/"min"; None -> [(labels, value), ...]). Aggregates
        over zero series return None — "no data" must not read as 0."""
        rows = [(labels, pts[-1][1])
                for labels, pts in self.series(name, match) if pts]
        if agg is None:
            return rows
        if not rows:
            return None
        vals = [v for _, v in rows]
        return {"sum": sum, "max": max, "min": min}[agg](vals)

    def _windowed(self, pts: List[Tuple[float, float]], now: float,
                  window_s: float) -> List[Tuple[float, float]]:
        """Points inside the window plus the last point BEFORE it (the
        baseline a counter delta needs — without it the first in-window
        increase is invisible)."""
        lo = now - window_s
        inside = [p for p in pts if p[0] >= lo]
        before = [p for p in pts if p[0] < lo]
        return ([before[-1]] if before else []) + inside

    def increase(self, name: str, window_s: float = 30.0,
                 match: Optional[dict] = None,
                 now: Optional[float] = None) -> float:
        """Counter increase over the window, summed across matching
        series. Reset-aware: a decrease (process restart) contributes
        the post-reset value, never a negative delta."""
        now = time.time() if now is None else now
        total = 0.0
        for _, pts in self.series(name, match):
            win = self._windowed(pts, now, window_s)
            for (t0, v0), (t1, v1) in zip(win, win[1:]):
                total += (v1 - v0) if v1 >= v0 else v1
        return total

    def rate(self, name: str, window_s: float = 30.0,
             match: Optional[dict] = None,
             now: Optional[float] = None) -> float:
        """increase()/sec over the ACTUAL observed span (clamped to the
        window) — a store holding 3 s of data asked for a 30 s rate
        divides by 3, not 30."""
        now = time.time() if now is None else now
        spans = []
        for _, pts in self.series(name, match):
            win = self._windowed(pts, now, window_s)
            if len(win) >= 2:
                spans.append(win[-1][0] - win[0][0])
        if not spans:
            return 0.0
        elapsed = min(max(spans), window_s)
        if elapsed <= 0:
            return 0.0
        return self.increase(name, window_s, match, now=now) / elapsed

    def mean(self, name: str, window_s: float = 60.0,
             match: Optional[dict] = None) -> Optional[float]:
        """Windowed mean of a histogram: Δ`name_sum` / Δ`name_count`
        across matching series (None when no events landed)."""
        now = time.time()
        dc = self.increase(f"{name}_count", window_s, match, now=now)
        if dc <= 0:
            return None
        return self.increase(f"{name}_sum", window_s, match, now=now) / dc

    def percentile(self, name: str, q: float, window_s: float = 60.0,
                   match: Optional[dict] = None) -> Optional[float]:
        """histogram_quantile over the windowed increase of the
        `{name}_bucket` series, merged across instances: per `le`
        boundary sum the increase, walk the cumulative counts to the
        q-rank, interpolate linearly inside the landing bucket. None
        when no events landed in the window."""
        now = time.time()
        by_le: Dict[float, float] = {}
        for labels, pts in self.series(f"{name}_bucket", match):
            le_raw = labels.get("le")
            if le_raw is None:
                continue
            le = float("inf") if le_raw == "+Inf" else float(le_raw)
            win = self._windowed(pts, now, window_s)
            inc = 0.0
            for (t0, v0), (t1, v1) in zip(win, win[1:]):
                inc += (v1 - v0) if v1 >= v0 else v1
            by_le[le] = by_le.get(le, 0.0) + inc
        if not by_le:
            return None
        bounds = sorted(by_le)
        total = by_le.get(float("inf"), 0.0) or max(by_le.values())
        if total <= 0:
            return None
        target = q * total
        cum = 0.0
        prev_bound, prev_cum = 0.0, 0.0
        for le in bounds:
            cum = by_le[le]   # buckets are CUMULATIVE per exposition spec
            if cum >= target and cum > prev_cum:
                hi = le if le != float("inf") else prev_bound
                frac = (target - prev_cum) / (cum - prev_cum)
                return prev_bound + (hi - prev_bound) * frac
            prev_bound = le if le != float("inf") else prev_bound
            prev_cum = max(prev_cum, cum)
        return prev_bound


class Scraper:
    """Polls every target's pulse `/metrics` into one TimeSeriesStore.

    Thread shape: ONE poll-loop daemon thread (`horizon-scrape`), started
    by `start()` and stopped via `_stop` (a threading.Event — the only
    cross-thread signal). The target list may be edited while the loop
    runs; it is copied under `_lock` per round.
    """

    def __init__(self, targets=None, interval_s: float = 1.0,
                 timeout_s: float = 2.0,
                 store: Optional[TimeSeriesStore] = None):
        self.store = store or TimeSeriesStore()
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._targets: List[Dict[str, str]] = []   # guarded_by: self._lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rounds = 0                           # guarded_by: self._lock
        for t in (targets or []):
            if isinstance(t, dict):
                self.add_target(t["job"], t["url"])
            else:
                job, url = t
                self.add_target(job, url)

    @staticmethod
    def _normalize_url(url) -> str:
        if isinstance(url, int):
            return f"http://127.0.0.1:{url}"
        url = str(url)
        if url.isdigit():        # bare port from a CLI arg
            return f"http://127.0.0.1:{url}"
        if "://" not in url:
            url = f"http://{url}"
        return url.rstrip("/")

    def add_target(self, job: str, url) -> str:
        """Register one pulse endpoint (`url` may be a full URL, a
        host:port, or a bare local port). Returns the normalized URL;
        duplicate registrations are idempotent."""
        url = self._normalize_url(url)
        with self._lock:
            if not any(t["url"] == url for t in self._targets):
                self._targets.append({"job": str(job), "url": url})
        return url

    def remove_target(self, url):
        url = self._normalize_url(url)
        with self._lock:
            self._targets = [t for t in self._targets if t["url"] != url]

    def targets(self) -> List[Dict[str, str]]:
        with self._lock:
            return [dict(t) for t in self._targets]

    # -- scraping ----------------------------------------------------------

    def _fetch(self, url: str) -> str:
        with urllib.request.urlopen(f"{url}/metrics",
                                    timeout=self.timeout_s) as resp:
            return resp.read().decode("utf-8", "replace")

    def poll_once(self) -> Dict[str, dict]:
        """One synchronous scrape round over every target. Returns
        per-url {"ok", "families", "error"}; a failing target is
        recorded as `horizon_up 0` and never raises."""
        results: Dict[str, dict] = {}
        ts = time.time()
        for t in self.targets():
            job, url = t["job"], t["url"]
            instance = url.split("://", 1)[-1]
            base = {"job": job, "instance": instance}
            try:
                families = _metrics.parse_prometheus_text(self._fetch(url))
                for fam in families.values():
                    for sname, labels, value in fam["samples"]:
                        self.store.add(sname, dict(labels, **base),
                                       value, ts=ts)
                self.store.add(UP_SERIES, base, 1.0, ts=ts)
                results[url] = {"ok": True, "families": len(families),
                                "error": None}
            except Exception as e:
                self.store.add(UP_SERIES, base, 0.0, ts=ts)
                results[url] = {"ok": False, "families": 0,
                                "error": f"{type(e).__name__}: {e}"}
        with self._lock:
            self._rounds += 1
        return results

    def rounds(self) -> int:
        with self._lock:
            return self._rounds

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:
                pass   # the plane outlives any one bad round

    def start(self) -> "Scraper":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="horizon-scrape")
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # -- derived fleet series ---------------------------------------------

    def fleet_overview(self, window_s: float = 30.0) -> dict:
        """The fleet-level derived series — what `tools/observatory.py
        --watch` tabulates and the fluid-tide controller will read.
        Every value is None (not 0) when no data supports it."""
        s = self.store
        up = s.latest(UP_SERIES)
        return {
            "targets": len(self.targets()),
            "targets_up": sum(1 for _, v in up if v >= 1.0) if up else 0,
            # replica-side accepted work (summed over models/outcomes)
            "serve_qps": s.rate("serve_requests_total", window_s),
            # router-side routed work (includes sheds/failovers)
            "fleet_qps": s.rate("fleet_requests_total", window_s),
            "request_p50_us": s.percentile("serve_request_latency_us",
                                           0.50, window_s),
            "request_p99_us": s.percentile("serve_request_latency_us",
                                           0.99, window_s),
            "max_ps_replication_lag": s.latest(
                "ps_replication_lag_updates", agg="max"),
            "decode_occupancy": s.mean("serve_decode_occupancy", window_s),
            "ps_rpc_qps": s.rate("pserver_client_requests_total", window_s),
            "master_tasks_todo": s.latest("master_tasks_todo", agg="sum"),
        }

    def snapshot(self, window_s: float = 30.0) -> dict:
        """One JSON-able document: targets, derived overview, and the
        newest value of every stored series (`tools/observatory.py
        --json`)."""
        latest = {}
        for name in self.store.names():
            latest[name] = [
                {"labels": labels, "value": value}
                for labels, value in self.store.latest(name) or []]
        return {"ts": time.time(), "targets": self.targets(),
                "overview": self.fleet_overview(window_s),
                "series": latest,
                "store": {"series": len(self.store),
                          "dropped_series": self.store.dropped_series()}}


def fetch_trace(url, timeout_s: float = 5.0) -> dict:
    """GET a pulse `/trace` endpoint: the target's live tracer ring as a
    chrome-trace document (what `tools/observatory.py --dump-trace`
    stitches across the fleet)."""
    url = Scraper._normalize_url(url)
    with urllib.request.urlopen(f"{url}/trace", timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))
