"""fluid-scope: unified runtime telemetry for paddle_tpu.

Three cooperating pieces (see docs/OBSERVABILITY.md):

- `observe.metrics`  — process-wide registry of counters / gauges /
  histograms (thread-safe, labeled, snapshot/JSON/Prometheus export)
- `observe.tracer`   — structured spans in a bounded ring buffer with
  chrome://tracing export; absorbs the profiler's host-event table
- `observe.steplog`  — per-run() StepStats phase timings + the
  recompilation observatory (every jit cache miss, with attributed cause)
- `observe.xray`     — W3C trace contexts across processes (round 11)
- `observe.flight`   — the crash flight recorder (round 11)
- `observe.pulse`    — per-process HTTP health endpoint: /metrics,
  /healthz, /readyz, /status, /flight (round 13, `start_pulse(port=0)`)
- `observe.health`   — metric time-series + anomaly detectors firing
  structured Alerts into the registry AND the flight ring (round 13)
- `observe.memory`   — the HBM observatory: per-program peak estimates
  vs live device memory stats (round 13)
- `observe.stitch`   — causal cross-process trace assembly: flow
  events + clock-skew correction over merged chrome traces (round 21)
- `observe.scrape`   — the fluid-horizon observatory: a scraper over
  every pulse /metrics into one queryable time-series store (round 21)

Emission from hot paths (Executor/PreparedProgram/ParallelExecutor steps,
AsyncFeeder, pserver RPC) is gated on the `observe` flag:

    fluid.set_flag("observe", True)        # or PADDLE_TPU_OBSERVE=1

With the flag off, the prepared-program fast path performs ZERO registry
writes per step (one flag read + branch only). Compile-time recompile
events are recorded regardless — they are never hot and they are what
`tools/telemetry_dump.py --assert-no-recompiles` audits in CI.
"""

from __future__ import annotations

from .. import flags as _flags
from . import flight, health, memory, metrics, pulse  # noqa: F401
from . import scrape, steplog, stitch, tracer, xray  # noqa: F401
from .flight import get_flight  # noqa: F401
from .health import get_engine  # noqa: F401
from .metrics import counter, default_registry, gauge, histogram  # noqa: F401
from .pulse import start_pulse, stop_pulse  # noqa: F401
from .scrape import Scraper, TimeSeriesStore  # noqa: F401
from .steplog import (StepStats, get_steplog, observatory,  # noqa: F401
                      preseed_shapes, track_shapes)
from .stitch import stitch_traces, trace_tree  # noqa: F401
from .tracer import get_tracer, merge_chrome_traces  # noqa: F401

# fluid-pulse: every flight-recorder dump carries the memory observatory
# (an OOM/SIGTERM death must be attributable to who held the bytes)
get_flight().add_section("memory", memory.get_observatory().flight_section)


def enabled() -> bool:
    """The hot-path gate: one flag-registry read."""
    return _flags.get_flag("observe")


def enable():
    _flags.set_flag("observe", True)


def disable():
    _flags.set_flag("observe", False)


def summary() -> dict:
    """One dict with everything a run left behind — what
    tools/telemetry_dump.py prints and bench.py records. Derived from
    pulse.status_document() (the live `/status` body) minus process
    identity, so the dead- and live-process shapes CANNOT diverge —
    one source of truth for the one-tool-reads-both contract."""
    doc = pulse.status_document()
    for k in ("pid", "process", "ts"):
        doc.pop(k, None)
    return doc


def reset():
    """Clear every telemetry store (tests / between bench segments)."""
    default_registry().reset()
    get_tracer().clear()
    get_steplog().clear()
    observatory().clear()


def reset_all():
    """`reset()` plus the fluid-xray stores (flight-recorder ring +
    stage, this thread's ambient trace context) and the fluid-pulse
    plane (the HTTP server thread is STOPPED, the health engine and
    memory observatory cleared). The tier-1 autouse fixture calls this
    so tests stop sharing process-global telemetry state — and can
    never leak a pulse thread."""
    reset()
    get_flight().clear()
    xray.reset()
    pulse.stop_pulse()
    health.reset()
    memory.reset()
