"""fluid-scope: unified runtime telemetry for paddle_tpu.

Three cooperating pieces (see docs/OBSERVABILITY.md):

- `observe.metrics`  — process-wide registry of counters / gauges /
  histograms (thread-safe, labeled, snapshot/JSON/Prometheus export)
- `observe.tracer`   — structured spans in a bounded ring buffer with
  chrome://tracing export; absorbs the profiler's host-event table
- `observe.steplog`  — per-run() StepStats phase timings + the
  recompilation observatory (every jit cache miss, with attributed cause)

Emission from hot paths (Executor/PreparedProgram/ParallelExecutor steps,
AsyncFeeder, pserver RPC) is gated on the `observe` flag:

    fluid.set_flag("observe", True)        # or PADDLE_TPU_OBSERVE=1

With the flag off, the prepared-program fast path performs ZERO registry
writes per step (one flag read + branch only). Compile-time recompile
events are recorded regardless — they are never hot and they are what
`tools/telemetry_dump.py --assert-no-recompiles` audits in CI.
"""

from __future__ import annotations

from .. import flags as _flags
from . import flight, metrics, steplog, tracer, xray  # noqa: F401
from .flight import get_flight  # noqa: F401
from .metrics import counter, default_registry, gauge, histogram  # noqa: F401
from .steplog import (StepStats, get_steplog, observatory,  # noqa: F401
                      preseed_shapes, track_shapes)
from .tracer import get_tracer, merge_chrome_traces  # noqa: F401


def enabled() -> bool:
    """The hot-path gate: one flag-registry read."""
    return _flags.get_flag("observe")


def enable():
    _flags.set_flag("observe", True)


def disable():
    _flags.set_flag("observe", False)


def summary() -> dict:
    """One dict with everything a run left behind — what
    tools/telemetry_dump.py prints and bench.py records."""
    return {
        "metrics": default_registry().snapshot(),
        "steps": get_steplog().phase_summary(),
        "recompiles": {
            "counts": observatory().counts(),
            "events": [e.as_dict() for e in observatory().events()],
        },
    }


def reset():
    """Clear every telemetry store (tests / between bench segments)."""
    default_registry().reset()
    get_tracer().clear()
    get_steplog().clear()
    observatory().clear()


def reset_all():
    """`reset()` plus the fluid-xray stores: flight-recorder ring +
    stage, and this thread's ambient trace context. The tier-1 autouse
    fixture calls this so tests stop sharing process-global telemetry
    state (snapshot-and-delta assertions are no longer required)."""
    reset()
    get_flight().clear()
    xray.reset()
