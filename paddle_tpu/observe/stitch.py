"""fluid-horizon stitching: causal cross-process trace assembly.

`tracer.merge_chrome_traces` puts every process's spans on one timeline,
but the result is still N parallel tracks: nothing in the merged file
SHOWS that the router's `fleet:infer` span caused the replica's
`replica:infer` which caused the pserver's `rpc_server:pull_sparse`.
This module turns the merge into a CAUSAL stitch:

- **Flow events.** Every cross-process parent→child span edge (the
  child's ``parent_span_id`` names a span recorded in a DIFFERENT
  process) becomes a chrome flow arrow (``ph:"s"`` at the client span,
  ``ph:"f"`` at the server span), so perfetto draws the request hopping
  router → replica → pserver instead of three unrelated tracks.

- **Clock-skew correction.** Per-process wall clocks drift; an
  uncorrected merge can show the server handler STARTING before the
  client sent the request. Every cross-process RPC edge gives one skew
  observation: the server span sits inside the client span's round
  trip, so ``offset = client_midpoint − server_midpoint`` estimates the
  server clock's error relative to the client (exact when the two
  network legs are symmetric). We take the median observation per
  directed process pair, then BFS the pair graph from a reference
  process, shifting every event of each reached process — the same
  midpoint estimator NTP uses, applied post-hoc.

- **Tree queries.** `trace_tree(events, trace_id)` indexes one trace's
  spans into roots/children/orphans so a drill (or the e2e pinned test)
  can assert "one trace, ≥3 processes, no orphans" in three lines.

Only spans carrying fluid-xray identity (``args.trace_id``/``span_id``)
participate in stitching; plain tracer spans ride through untouched.
"""

from __future__ import annotations

import json
import statistics
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from . import tracer as _tracer


def _span_args(ev: dict) -> dict:
    a = ev.get("args")
    return a if isinstance(a, dict) else {}


def _xray_spans(events: Sequence[dict]) -> List[dict]:
    """The "X" events carrying fluid-xray identity."""
    return [ev for ev in events
            if ev.get("ph") == "X" and _span_args(ev).get("span_id")]


def span_index(events: Sequence[dict]) -> Dict[Tuple[str, str], dict]:
    """(trace_id, span_id) -> event, over xray-identified spans. A
    duplicate identity keeps the FIRST occurrence (per-attempt retry
    spans always allocate fresh ids, so duplicates only arise from
    merging the same file twice — harmless either way)."""
    idx: Dict[Tuple[str, str], dict] = {}
    for ev in _xray_spans(events):
        a = _span_args(ev)
        idx.setdefault((a["trace_id"], a["span_id"]), ev)
    return idx


def cross_process_edges(events: Sequence[dict]) -> List[Tuple[dict, dict]]:
    """Every (parent_event, child_event) pair where the child's
    parent_span_id resolves to a span recorded under a DIFFERENT pid —
    i.e. the causal hops a flow arrow should draw."""
    idx = span_index(events)
    edges = []
    for ev in _xray_spans(events):
        a = _span_args(ev)
        parent_id = a.get("parent_span_id")
        if not parent_id:
            continue
        parent = idx.get((a["trace_id"], parent_id))
        if parent is not None and parent.get("pid") != ev.get("pid"):
            edges.append((parent, ev))
    return edges


def _midpoint_us(ev: dict) -> float:
    return ev.get("ts", 0) + ev.get("dur", 0) / 2.0


def estimate_skew_us(events: Sequence[dict],
                     reference_pid: Optional[int] = None
                     ) -> Dict[int, float]:
    """Per-pid clock offset (µs to ADD to that pid's timestamps), from
    cross-process RPC edges: each edge's server span nests inside the
    client's round trip, so client_mid − server_mid observes the server
    clock's error. Median per directed pid pair, then BFS from
    `reference_pid` (default: the pid with the most xray spans) so
    indirectly-connected processes (trainer→pserver→haven backup) are
    corrected transitively. Pids unreachable from the reference keep
    offset 0 — an uncorrectable clock is left honest, not guessed."""
    spans = _xray_spans(events)
    if not spans:
        return {}
    if reference_pid is None:
        counts: Dict[int, int] = {}
        for ev in spans:
            counts[ev.get("pid", 0)] = counts.get(ev.get("pid", 0), 0) + 1
        reference_pid = max(counts, key=lambda p: (counts[p], -p))
    # directed pair (client_pid, server_pid) -> skew observations
    obs: Dict[Tuple[int, int], List[float]] = {}
    for parent, child in cross_process_edges(events):
        key = (parent.get("pid", 0), child.get("pid", 0))
        obs.setdefault(key, []).append(
            _midpoint_us(parent) - _midpoint_us(child))
    # undirected adjacency with the median offset in the client->server
    # direction (server_offset = client_offset + median)
    adj: Dict[int, List[Tuple[int, float]]] = {}
    for (cpid, spid), vals in obs.items():
        med = statistics.median(vals)
        adj.setdefault(cpid, []).append((spid, med))
        adj.setdefault(spid, []).append((cpid, -med))
    offsets: Dict[int, float] = {reference_pid: 0.0}
    q = deque([reference_pid])
    while q:
        pid = q.popleft()
        for other, delta in adj.get(pid, []):
            if other not in offsets:
                offsets[other] = offsets[pid] + delta
                q.append(other)
    return offsets


def stitch_traces(paths: Sequence[str], out_path: Optional[str] = None,
                  strict: bool = False, skew_correct: bool = True
                  ) -> Tuple[dict, dict]:
    """Merge per-process chrome traces AND make the result causal:
    clock-skew-correct each process onto the reference clock, then emit
    flow events for every cross-process span edge. Returns
    (stitched_doc, stats); stats extends the merge stats with
    ``edges`` (flow arrows emitted), ``skew_us`` (per-pid applied
    shift), and ``orphans`` (xray spans whose parent id resolves
    nowhere in the merge — 0 in a healthy full capture)."""
    doc, stats = _tracer.merge_chrome_traces(paths, strict=strict)
    events = doc["traceEvents"]
    spans = [ev for ev in events if ev.get("ph") != "M"]
    if skew_correct:
        offsets = estimate_skew_us(spans)
        for ev in spans:
            off = offsets.get(ev.get("pid", 0), 0.0)
            if off:
                ev["ts"] = int(ev.get("ts", 0) + off)
        stats["skew_us"] = {str(pid): round(off, 1)
                            for pid, off in offsets.items() if off}
    else:
        stats["skew_us"] = {}
    flows: List[dict] = []
    for i, (parent, child) in enumerate(cross_process_edges(spans)):
        trace_id = _span_args(child).get("trace_id", "")
        flow = {"cat": "xray_flow", "name": "xray",
                "id": f"{trace_id[:8]}:{i}"}
        flows.append(dict(flow, ph="s", pid=parent["pid"],
                          tid=parent.get("tid", 0),
                          ts=int(_midpoint_us(parent))))
        flows.append(dict(flow, ph="f", bp="e", pid=child["pid"],
                          tid=child.get("tid", 0),
                          ts=int(child.get("ts", 0))))
    stats["edges"] = len(flows) // 2
    idx = span_index(spans)
    orphans = []
    for ev in _xray_spans(spans):
        a = _span_args(ev)
        pid_ = a.get("parent_span_id")
        if pid_ and (a["trace_id"], pid_) not in idx:
            orphans.append(a.get("span_id"))
    stats["orphans"] = len(orphans)
    spans.sort(key=lambda e: e.get("ts", 0))
    meta = [ev for ev in events if ev.get("ph") == "M"]
    doc = {"traceEvents": meta + spans + flows, "displayTimeUnit": "ms"}
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump(doc, f)
    return doc, stats


def trace_tree(events: Sequence[dict], trace_id: str) -> dict:
    """Index ONE trace's spans into a parentage tree:

        {"roots": [event...],              # spans with no parent
         "orphans": [event...],            # parent id resolves nowhere
         "children": {span_id: [event...]},
         "spans": {span_id: event},
         "pids": {pid...}}

    The e2e contract a stitched capture must satisfy: one root, zero
    orphans, and `pids` spanning every process the request touched."""
    spans = [ev for ev in _xray_spans(events)
             if _span_args(ev).get("trace_id") == trace_id]
    by_id = {_span_args(ev)["span_id"]: ev for ev in spans}
    roots, orphans = [], []
    children: Dict[str, List[dict]] = {}
    for ev in spans:
        parent_id = _span_args(ev).get("parent_span_id")
        if not parent_id:
            roots.append(ev)
        elif parent_id in by_id:
            children.setdefault(parent_id, []).append(ev)
        else:
            orphans.append(ev)
    return {"roots": roots, "orphans": orphans, "children": children,
            "spans": by_id, "pids": {ev.get("pid") for ev in spans}}
