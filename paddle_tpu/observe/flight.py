"""Crash flight recorder: a bounded black box that survives the crash.

The chaos harness (ark/) deliberately kills processes, and the bench
driver SIGTERMs runs that overshoot their budget — and until now both
left only a log tail. The flight recorder keeps a bounded ring of the
most recent *operationally interesting* records — step summaries, RPC
outcomes, compile events, lease transitions, chaos injections — plus a
named "stage", and dumps the whole thing as JSON when the process dies
abnormally (SIGTERM, unhandled exception, or an explicit `dump()` from
a crash path such as bench.py's wakeup-fd watcher).

Recording is an O(1) deque append under a lock; emitters gate on the
`observe` flag exactly like the metrics registry where the path is hot
(per-step records), and record unconditionally where it is not
(compiles, lease transitions — events measured in seconds, recorded in
microseconds).

The dump is plain JSON, newest-last, with enough identity (pid, process
name, stage, reason) that a postmortem can be read standalone:

    {"pid": ..., "process": "trainer0", "reason": "SIGTERM", ...,
     "failure_stage": "transformer2048_unfused",
     "events": [{"ts": ..., "kind": "step", ...}, ...]}
"""

from __future__ import annotations

import json
import os
import signal as _signal
import sys
import threading
import time
import traceback
import math
from collections import deque
from typing import Callable, List, Optional

DEFAULT_CAPACITY = 512

#: env override for where an UNINSTALLED recorder dumps (drills that
#: never call install() used to litter `flight_recorder.json` into the
#: CWD — i.e. the repo root when run from a checkout)
DUMP_PATH_ENV = "PADDLE_TPU_FLIGHT_PATH"


def default_dump_path() -> str:
    """The dump path when neither dump(path=...) nor install(path=...)
    named one: `$PADDLE_TPU_FLIGHT_PATH` if set, else a pid-suffixed
    file under the system temp dir — NEVER the current directory."""
    env = os.environ.get(DUMP_PATH_ENV)
    if env:
        return env
    import tempfile
    return os.path.join(tempfile.gettempdir(),
                        f"flight_recorder.{os.getpid()}.json")


def json_safe(v):
    """RFC 8259 has no NaN/Infinity but Python's json emits bare `NaN`
    tokens — a postmortem (or /healthz body) carrying a non-finite
    observed value must still parse in strict readers. Stringify
    non-finite floats recursively."""
    if isinstance(v, float) and not math.isfinite(v):
        return repr(v)          # 'nan' / 'inf' / '-inf', as a string
    if isinstance(v, dict):
        return {k: json_safe(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [json_safe(x) for x in v]
    return v


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._stage: Optional[str] = None
        self._dump_path: Optional[str] = None
        self._extra_dump: Optional[Callable] = None
        self._installed = False
        self._prev_excepthook = None
        self._dumped = threading.Event()
        # named snapshot providers merged into every dump (fluid-pulse
        # registers "memory" here so an OOM/SIGTERM death carries the
        # HBM observatory). Providers survive clear() — they are wiring,
        # not state.
        self._sections: dict = {}

    def add_section(self, name: str, fn: Callable):
        """Merge `fn()` into every snapshot under `name`, best-effort (a
        failing provider is dropped from that dump, never raises)."""
        self._sections[name] = fn

    # -- recording --------------------------------------------------------

    def note(self, kind: str, **data):
        """Append one record. Cheap (deque append) but not free — hot
        paths gate on the `observe` flag before calling."""
        ev = {"ts": time.time(), "kind": kind}
        ev.update(data)
        with self._lock:
            self._events.append(ev)

    def set_stage(self, stage: Optional[str]):
        """Name the phase the process is in (bench segment, drill
        scenario, epoch...) — dumped as `failure_stage`."""
        self._stage = stage

    def stage(self) -> Optional[str]:
        return self._stage

    def events(self, kind: Optional[str] = None) -> List[dict]:
        with self._lock:
            evs = list(self._events)
        if kind is not None:
            evs = [e for e in evs if e["kind"] == kind]
        return evs

    def clear(self):
        with self._lock:
            self._events.clear()
        self._stage = None

    def __len__(self):
        with self._lock:
            return len(self._events)

    # -- dumping ----------------------------------------------------------

    def snapshot(self, reason: Optional[str] = None) -> dict:
        from . import xray as _xray
        with self._lock:
            evs = list(self._events)
        doc = {
            "pid": os.getpid(),
            "process": _xray.process_name(),
            "dumped_at": time.time(),
            "reason": reason,
            "failure_stage": self._stage,
            "events": evs,
        }
        for name, fn in list(self._sections.items()):
            try:
                doc[name] = fn()
            except Exception:
                pass
        return doc

    def dump(self, path: Optional[str] = None,
             reason: Optional[str] = None) -> Optional[str]:
        """Write the black box as JSON. `path` defaults to the installed
        path (install()), then `$PADDLE_TPU_FLIGHT_PATH`, then a
        pid-suffixed file under the system temp dir — never the CWD (a
        drill run from a checkout must not litter the repo root). Never
        raises — a failing postmortem writer must not mask the original
        crash; returns the path written or None."""
        path = path or self._dump_path or default_dump_path()
        try:
            snap = json_safe(self.snapshot(reason=reason))
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(snap, f, indent=1, default=str)
            os.replace(tmp, path)  # a torn dump never shadows a good one
            self._dumped.set()
            return path
        except Exception:
            return None

    # -- crash hooks ------------------------------------------------------

    def install(self, path: str, signals=(getattr(_signal, "SIGTERM", None),),
                excepthook: bool = True,
                extra: Optional[Callable] = None):
        """Arm the black box: dump to `path` on the given signals and on
        unhandled exceptions. `extra` (e.g. a tracer chrome export) runs
        after the dump, best-effort. Signal handlers hard-exit (code 1)
        after dumping — the process was being killed anyway, and a
        half-torn-down runtime should not keep running.

        Only usable from the main thread (CPython signal rule); bench.py
        keeps its own wakeup-fd watcher and just calls `dump()`."""
        self._dump_path = path
        self._extra_dump = extra
        if not self._installed and excepthook:
            self._prev_excepthook = sys.excepthook

            def _hook(exc_type, exc, tb):
                self.note("unhandled_exception",
                          error=f"{exc_type.__name__}: {exc}",
                          traceback="".join(
                              traceback.format_tb(tb))[-2000:])
                self.dump(reason=f"unhandled {exc_type.__name__}")
                self._run_extra()
                (self._prev_excepthook or sys.__excepthook__)(
                    exc_type, exc, tb)

            sys.excepthook = _hook
        for sig in signals:
            if sig is None:
                continue

            def _on_signal(signum, frame, _self=self):
                _self.note("signal", signum=int(signum))
                _self.dump(reason=f"signal {int(signum)}")
                _self._run_extra()
                os._exit(1)

            _signal.signal(sig, _on_signal)
        self._installed = True

    def _run_extra(self):
        if self._extra_dump is not None:
            try:
                self._extra_dump()
            except Exception:
                pass


_recorder = FlightRecorder()


def get_flight() -> FlightRecorder:
    return _recorder


def note(kind: str, **data):
    _recorder.note(kind, **data)


def set_stage(stage: Optional[str]):
    _recorder.set_stage(stage)


def dump(path: Optional[str] = None, reason: Optional[str] = None):
    return _recorder.dump(path=path, reason=reason)


def install(path: str, **kw):
    _recorder.install(path, **kw)
