"""Process-wide metrics registry: counters / gauges / histograms.

Reference analog: the profiler event tables of platform/profiler.cc gave
Fluid aggregate counts; TensorFlow's whitepaper credits built-in metrics
plumbing for making large-scale training debuggable. Here the registry is
a plain thread-safe in-process store — no exporter daemon, no deps — with
`snapshot()` (dict), `to_json()` and `to_prometheus()` (text exposition
format) so a training loop, bench.py, or tools/telemetry_dump.py can dump
it at any point.

All three metric kinds support labels passed as keyword arguments:

    counter("pserver_client_requests_total").inc(cmd="push_grad")
    histogram("executor_step_phase_us").observe(12.5, phase="feed_convert")

Writers are cheap (one lock + dict update) but NOT free: runtime emitters
gate on the `observe` flag so the prepared-executor hot path stays clean
when telemetry is off.
"""

from __future__ import annotations

import bisect
import json
import math
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

# wide geometric default buckets: usable for µs phase timings and for
# second-scale RPC latencies alike (callers pick the unit, the buckets
# span 1e-6 .. 1e6)
DEFAULT_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0, 100.0,
                   1e3, 1e4, 1e5, 1e6)


def _label_key(labels: Dict[str, Any]) -> Tuple:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _label_str(key: Tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in key)


def _prom_escape(v: str) -> str:
    """Label-VALUE escaping per the exposition spec: backslash first (or
    the other escapes would double-escape), then double-quote and
    newline. A label value containing any of the three can no longer
    corrupt a scrape — pinned by the strict round-trip test."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _prom_escape_help(v: str) -> str:
    """HELP-text escaping: the spec escapes backslash and line feed only
    (a double-quote is legal in help text)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _prom_value(v) -> str:
    """Sample-value formatting: Python would print `inf`/`nan`, which the
    exposition grammar rejects — Prometheus spells them `+Inf`/`-Inf`/
    `NaN`."""
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    return repr(f) if isinstance(v, float) else str(v)


def _prom_labels(key: Tuple, extra: str = "") -> str:
    parts = [f'{k}="{_prom_escape(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[Tuple, Any] = {}
        # write-path hooks (observe.health rides these to feed bounded
        # TimeSeries rings): a tuple so the unwatched hot path pays one
        # attribute load + falsy test, nothing else
        self._watchers: Tuple = ()

    def clear(self):
        with self._lock:
            self._values.clear()

    def labelsets(self):
        with self._lock:
            return list(self._values)

    def items(self):
        """[(labels_dict, value)] over every label set. For histograms
        the value is the internal bucket state — use summary() there."""
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]

    # scalar (counter/gauge) serialization; Histogram overrides both
    def _snapshot(self):
        with self._lock:
            return {_label_str(k): v for k, v in self._values.items()}

    def _prometheus(self, lines):
        with self._lock:
            for k, v in sorted(self._values.items()):
                lines.append(f"{self.name}{_prom_labels(k)} {_prom_value(v)}")

    def _notify(self, v, k):
        # called OUTSIDE the value lock: a watcher appending to its own
        # ring must not be able to deadlock against a concurrent writer
        for w in self._watchers:
            try:
                w(v, k)
            except Exception:
                pass


class Counter(_Metric):
    kind = "counter"

    def inc(self, n: float = 1, **labels):
        k = _label_key(labels)
        with self._lock:
            self._values[k] = self._values.get(k, 0) + n
        if self._watchers:
            self._notify(n, k)   # watchers see the INCREMENT (rates)

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0)

    def total(self) -> float:
        """Sum over every label set."""
        with self._lock:
            return sum(self._values.values())


class Gauge(_Metric):
    kind = "gauge"

    def set(self, v: float, **labels):
        k = _label_key(labels)
        with self._lock:
            self._values[k] = v
        if self._watchers:
            self._notify(v, k)

    def inc(self, n: float = 1, **labels):
        k = _label_key(labels)
        with self._lock:
            v = self._values[k] = self._values.get(k, 0) + n
        if self._watchers:
            self._notify(v, k)   # watchers see the new LEVEL

    def dec(self, n: float = 1, **labels):
        self.inc(-n, **labels)

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._values.get(_label_key(labels))


class Histogram(_Metric):
    """Fixed-bucket histogram. Per label set it keeps cumulative bucket
    counts plus sum/count/min/max, so `summary()` can report a mean and
    envelope without storing samples."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", buckets=DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(buckets))

    def observe(self, v: float, **labels):
        k = _label_key(labels)
        with self._lock:
            st = self._values.get(k)
            if st is None:
                st = self._values[k] = {
                    "buckets": [0] * (len(self.buckets) + 1),
                    "sum": 0.0, "count": 0,
                    "min": float("inf"), "max": float("-inf")}
            st["buckets"][bisect.bisect_left(self.buckets, v)] += 1
            st["sum"] += v
            st["count"] += 1
            st["min"] = min(st["min"], v)
            st["max"] = max(st["max"], v)
        if self._watchers:
            self._notify(v, k)   # watchers see the raw SAMPLE

    def summary(self, **labels) -> Optional[dict]:
        with self._lock:
            st = self._values.get(_label_key(labels))
            if st is None:
                return None
            return {"count": st["count"], "sum": st["sum"],
                    "mean": st["sum"] / max(st["count"], 1),
                    "min": st["min"], "max": st["max"]}

    QUANTILES = (0.5, 0.9, 0.99)

    def _estimate_quantiles(self, st, qs=QUANTILES) -> Dict[float, float]:
        """Bucket-interpolated quantile estimates (the classic Prometheus
        histogram_quantile): walk the cumulative bucket counts to the
        target rank, interpolate linearly inside the landing bucket, and
        clamp to the observed [min, max] envelope (which also makes a
        single-sample histogram report that sample exactly)."""
        counts = st["buckets"]
        total = st["count"]
        out: Dict[float, float] = {}
        if total <= 0:
            return out
        for q in qs:
            target = q * total
            cum = 0.0
            v = st["max"]
            for i, n in enumerate(counts):
                cum += n
                if cum >= target and n > 0:
                    lo = self.buckets[i - 1] if i > 0 else min(
                        st["min"], self.buckets[0])
                    hi = self.buckets[i] if i < len(self.buckets) \
                        else st["max"]
                    frac = (target - (cum - n)) / n
                    v = lo + (hi - lo) * frac
                    break
            out[q] = min(max(v, st["min"]), st["max"])
        return out

    def quantiles(self, qs=QUANTILES, **labels) -> Optional[Dict[float, float]]:
        with self._lock:
            st = self._values.get(_label_key(labels))
            if st is None:
                return None
            return self._estimate_quantiles(st, qs)

    def _snapshot(self):
        with self._lock:
            out = {}
            for k, st in self._values.items():
                out[_label_str(k)] = {
                    "count": st["count"], "sum": round(st["sum"], 9),
                    "mean": round(st["sum"] / max(st["count"], 1), 9),
                    "min": st["min"], "max": st["max"]}
            return out

    def _prometheus(self, lines):
        qlines = []
        with self._lock:
            for k, st in sorted(self._values.items()):
                cum = 0
                for ub, n in zip(self.buckets, st["buckets"]):
                    cum += n
                    le = 'le="%s"' % ub
                    lines.append(f"{self.name}_bucket"
                                 f"{_prom_labels(k, le)} {cum}")
                cum += st["buckets"][-1]
                inf = 'le="+Inf"'
                lines.append(f"{self.name}_bucket"
                             f"{_prom_labels(k, inf)} {cum}")
                lines.append(f"{self.name}_sum{_prom_labels(k)} "
                             f"{_prom_value(st['sum'])}")
                lines.append(f"{self.name}_count{_prom_labels(k)} "
                             f"{st['count']}")
                for q, v in sorted(self._estimate_quantiles(st).items()):
                    ql = f'quantile="{q}"'
                    qlines.append(f"{self.name}_quantile"
                                  f"{_prom_labels(k, ql)} "
                                  f"{_prom_value(float(f'{v:.9g}'))}")
        # estimated p50/p90/p99 as a SEPARATE `<name>_quantile` gauge
        # family: dashboards get latency percentiles without a
        # histogram_quantile() recording rule, and strict scrapers stay
        # happy (quantile samples on the bare name are only legal under
        # TYPE summary)
        if qlines:
            lines.append(f"# TYPE {self.name}_quantile gauge")
            lines.extend(qlines)


class Registry:
    """Name -> metric store. `counter`/`gauge`/`histogram` are
    get-or-create; asking for an existing name with a different kind is a
    programming error and raises."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        # name -> [watch fns]: attached to the metric object at creation,
        # so a watch installed BEFORE the metric first emits still sees
        # every write (observe.health arms its detectors this way)
        self._watches: Dict[str, list] = {}
        # bumped on reset() so holders of cached metric handles (e.g. the
        # steplog's hot path) can detect that their handle was orphaned
        self._generation = 0

    def generation(self) -> int:
        return self._generation

    def _get_or_create(self, cls, name, help, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
                if name in self._watches:
                    m._watchers = tuple(self._watches[name])
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def watch(self, name: str, fn) -> int:
        """Mirror every write of metric `name` into `fn(value, label_key)`
        — counters pass the increment, gauges the new level, histograms
        the raw sample. O(1) on the write path; metrics created later
        pick the watch up at creation. Cleared by reset(). Returns the
        generation the watch was registered INTO (read under the same
        lock reset() takes), so a re-arming caller can stamp exactly
        which generation its sink lives in — no TOCTOU against a
        concurrent reset."""
        with self._lock:
            fns = self._watches.setdefault(name, [])
            fns.append(fn)
            m = self._metrics.get(name)
            if m is not None:
                m._watchers = tuple(fns)
            return self._generation

    def unwatch(self, name: str, fn) -> None:
        """Detach one watch fn (health-engine reset: orphaned sinks must
        not keep feeding dead rings on the hot write path)."""
        with self._lock:
            fns = self._watches.get(name)
            if not fns or fn not in fns:
                return
            fns.remove(fn)
            if not fns:
                self._watches.pop(name)
            m = self._metrics.get(name)
            if m is not None:
                m._watchers = tuple(fns)

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self):
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self) -> Dict[str, dict]:
        """JSON-safe dict: name -> {kind, help, values: {labelstr: v}}."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: {"kind": m.kind, "help": m.help,
                         "values": m._snapshot()} for m in metrics}

    def to_json(self, indent=None) -> str:
        return json.dumps(self.snapshot(), indent=indent, sort_keys=True)

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (scrape-compatible)."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_prom_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            m._prometheus(lines)
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self):
        """Drop every metric (definitions AND watches)."""
        with self._lock:
            self._metrics.clear()
            self._watches.clear()
            self._generation += 1


_registry = Registry()


def default_registry() -> Registry:
    return _registry


def counter(name: str, help: str = "") -> Counter:
    return _registry.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return _registry.gauge(name, help)


def histogram(name: str, help: str = "", buckets=DEFAULT_BUCKETS) -> Histogram:
    return _registry.histogram(name, help, buckets=buckets)


# ---------------------------------------------------------------------------
# strict exposition-format parser (fluid-pulse)
# ---------------------------------------------------------------------------
# The round-trip pin for to_prometheus(): every line a scrape produces
# must match the text-exposition grammar EXACTLY, and label values
# containing `\`, `"` or a newline must come back byte-identical. Also
# what tests/pulse use to prove a live /metrics scrape is well-formed.

_METRIC_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABEL_PAIR_RE = (r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*"')
_VALUE_RE = (r"[+-]?(?:[0-9]+(?:\.[0-9]*)?|\.[0-9]+)(?:[eE][+-]?[0-9]+)?"
             r"|[+-]?Inf|NaN")
_SAMPLE_RE = re.compile(
    rf"^(?P<name>{_METRIC_NAME_RE})"
    rf"(?:\{{(?P<labels>{_LABEL_PAIR_RE}(?:,{_LABEL_PAIR_RE})*)?\}})?"
    rf" (?P<value>{_VALUE_RE})$")
_LABEL_RE = re.compile(
    r'(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\\n]|\\\\|\\"|\\n)*)"'
    r"(?:,|$)")
_HELP_RE = re.compile(rf"^# HELP (?P<name>{_METRIC_NAME_RE}) (?P<help>.*)$")
_TYPE_RE = re.compile(
    rf"^# TYPE (?P<name>{_METRIC_NAME_RE}) "
    r"(?P<kind>counter|gauge|histogram|summary|untyped)$")


def _unescape(v: str, what: str, quote_ok: bool) -> str:
    """Left-to-right escape scan — sequential str.replace would corrupt
    e.g. an escaped backslash followed by a literal `n` (`\\\\n` must
    become backslash+n, not a newline)."""
    out, i = [], 0
    while i < len(v):
        c = v[i]
        if c == "\\" and i + 1 < len(v):
            nxt = v[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"' and quote_ok:
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:
                raise ValueError(f"illegal escape \\{nxt} in {what}")
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _unescape_label(v: str) -> str:
    return _unescape(v, "label value", quote_ok=True)


def _parse_value(s: str) -> float:
    if s in ("+Inf", "Inf"):
        return float("inf")
    if s == "-Inf":
        return float("-inf")
    if s == "NaN":
        return float("nan")
    return float(s)


def parse_prometheus_text(text: str) -> Dict[str, dict]:
    """STRICT line-grammar parse of a text-exposition document.

    Returns ``{family: {"kind", "help", "samples": [(name, labels, value),
    ...]}}`` where `labels` is a dict with values UN-escaped. Raises
    ``ValueError`` naming the first malformed line — this is the
    round-trip gate, not a lenient scraper."""
    out: Dict[str, dict] = {}

    def family(name: str) -> dict:
        base = name
        for suf in ("_bucket", "_count", "_sum"):
            if base.endswith(suf) and base[: -len(suf)] in out:
                base = base[: -len(suf)]
                break
        return out.setdefault(base, {"kind": None, "help": None,
                                     "samples": []})

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line:
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line)
            if m:
                # reverse of _prom_escape_help (\\ and \n only — a raw
                # quote in help text is legal and never escaped)
                family(m.group("name"))["help"] = _unescape(
                    m.group("help"), "help text", quote_ok=False)
                continue
            m = _TYPE_RE.match(line)
            if m:
                family(m.group("name"))["kind"] = m.group("kind")
                continue
            raise ValueError(f"line {lineno}: malformed comment: {line!r}")
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample: {line!r}")
        labels: Dict[str, str] = {}
        raw = m.group("labels")
        if raw:
            consumed = 0
            for lm in _LABEL_RE.finditer(raw):
                if lm.start() != consumed:
                    raise ValueError(
                        f"line {lineno}: malformed labels: {raw!r}")
                labels[lm.group("k")] = _unescape_label(lm.group("v"))
                consumed = lm.end()
            if consumed != len(raw):
                raise ValueError(f"line {lineno}: malformed labels: {raw!r}")
        try:
            value = _parse_value(m.group("value"))
        except ValueError:
            raise ValueError(
                f"line {lineno}: malformed value: {m.group('value')!r}")
        family(m.group("name"))["samples"].append(
            (m.group("name"), labels, value))
    return out
