"""fluid-pulse: the HBM/memory observatory.

HBM is the scarcest TPU resource and, until now, the least observable
one: an OOM death left a log tail and no account of WHO held the bytes.
This module keeps a per-process ledger of per-program peak-HBM
*estimates* (analysis.cost_model.estimate_peak_hbm over the concrete
shapes each program actually bound) and compares them against LIVE
device memory stats whenever a real backend exposes them.

Degradation contract: probe `jax.devices()` first; a backend without
`memory_stats()` (the CPU mesh every tier-1 test runs on) degrades to
estimate-only — silently, once, never a warning per call and never an
error. The observatory must be safe to consult from a signal handler
(the flight recorder dumps a memory section on OOM/SIGTERM), so every
public entry point swallows backend exceptions.

Estimates are recorded at executor compile time (never hot, and only
while the `observe` flag is on); bench.py reads `segment_peak()` per
segment and `tools/telemetry_dump.py` / the pulse `/status` endpoint
render `report()`.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

_LIVE_STAT_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                   "largest_free_block_bytes", "pool_bytes")


class MemoryObservatory:
    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        # key -> {"name", "source", "ts", estimate fields...}
        self._programs: Dict[str, dict] = {}
        self._capacity = capacity
        self._segment_peak = 0.0
        self._live_probed = False
        self._live_available = False
        # last successful probe, served by flight_section(): a crash
        # dump must never talk to a (possibly wedged) backend
        self._last_live: Optional[List[dict]] = None

    # -- estimates --------------------------------------------------------

    def note_program(self, program, feed_arrays: Dict, source: str =
                     "executor", name: Optional[str] = None) -> Optional[dict]:
        """Record the peak-HBM estimate of `program` bound with the
        concrete `feed_arrays` shapes. Called from the executor's
        compile path (a compile costs seconds, the shape walk costs
        milliseconds); one entry per (program, feed-shape signature).
        Never raises."""
        try:
            feed_shapes = {n: tuple(getattr(v, "shape", ()))
                           for n, v in feed_arrays.items()}
            key = (f"{name or 'prog'}#{getattr(program, '_uid', 0)}@"
                   + ",".join(f"{n}:{'x'.join(map(str, s))}"
                              for n, s in sorted(feed_shapes.items())))
            with self._lock:
                if key in self._programs:
                    return self._programs[key]
            from ..analysis import cost_model as _cm
            est = _cm.estimate_peak_hbm(program, feed_shapes)
            rec = dict(est, name=name or f"prog{getattr(program, '_uid', 0)}",
                       source=source, ts=time.time())
            with self._lock:
                if len(self._programs) >= self._capacity:
                    # drop the oldest entry — a long-lived server loading
                    # many model versions must not grow unboundedly
                    oldest = min(self._programs,
                                 key=lambda k: self._programs[k]["ts"])
                    self._programs.pop(oldest)
                self._programs[key] = rec
                self._segment_peak = max(self._segment_peak,
                                         rec["peak_bytes"])
            return rec
        except Exception:
            return None

    def programs(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._programs)

    def estimate_peak_bytes(self) -> float:
        """The largest single-program peak estimate currently tracked —
        programs don't all run at once, so the max (not the sum) is the
        honest single-number estimate."""
        with self._lock:
            return max((r["peak_bytes"] for r in self._programs.values()),
                       default=0.0)

    def segment_peak(self, reset: bool = False) -> float:
        """Max peak estimate recorded since the last reset (bench.py
        reads this per segment)."""
        with self._lock:
            v = self._segment_peak
            if reset:
                self._segment_peak = 0.0
            return v

    # -- live device stats ------------------------------------------------

    def live_device_stats(self) -> Optional[List[dict]]:
        """Per-device memory stats from the jax backend, or None when the
        backend exposes none (CPU) — the estimate-only degradation. No
        warnings either way; `live_available()` says which mode we are
        in."""
        try:
            import jax
            devices = jax.devices()
        except Exception:
            self._live_probed = True
            self._live_available = False
            return None
        out = []
        for d in devices:
            try:
                st = d.memory_stats()
            except Exception:
                st = None
            if not isinstance(st, dict) or not st:
                continue
            rec = {"device": str(d), "platform": getattr(d, "platform", "?")}
            for k in _LIVE_STAT_KEYS:
                if k in st:
                    rec[k] = int(st[k])
            out.append(rec)
        self._live_probed = True
        self._live_available = bool(out)
        if out:
            self._last_live = out
        return out or None

    def live_available(self) -> bool:
        if not self._live_probed:
            self.live_device_stats()
        return self._live_available

    # -- reports ----------------------------------------------------------

    def report(self) -> dict:
        """The memory section of /status, telemetry dumps, and the flight
        recorder: tracked per-program estimates, the honest aggregate,
        and — when a real backend exists — live bytes with a
        proportional-share attribution across the tracked programs."""
        progs = self.programs()
        live = self.live_device_stats()
        est_total = sum(r["peak_bytes"] for r in progs.values())
        doc: dict = {
            "live": live is not None,
            "estimate_peak_bytes": self.estimate_peak_bytes(),
            "programs": {
                k: {f: r[f] for f in
                    ("name", "source", "param_bytes",
                     "optimizer_slot_bytes", "grad_bytes",
                     "activation_bytes", "feed_bytes", "peak_bytes")}
                for k, r in progs.items()},
        }
        if live is not None:
            doc["devices"] = live
            in_use = sum(d.get("bytes_in_use", 0) for d in live)
            doc["bytes_in_use"] = in_use
            doc["peak_bytes_in_use"] = sum(
                d.get("peak_bytes_in_use", 0) for d in live)
            if est_total > 0 and in_use > 0:
                # attribution heuristic, clearly labeled: live bytes
                # split across tracked programs proportionally to their
                # estimates (jax exposes no per-executable accounting)
                for r in doc["programs"].values():
                    r["attributed_live_bytes"] = int(
                        in_use * (r["peak_bytes"] / est_total))
        return doc

    def flight_section(self) -> dict:
        """Compact variant for the flight recorder (a dump must stay
        readable): aggregate numbers + the top-4 programs by estimate.
        Runs inside signal handlers — serves the LAST-KNOWN device
        stats and never probes the backend (a wedged/OOMing runtime
        could hang the dying process mid-dump)."""
        progs = sorted(self.programs().values(),
                       key=lambda r: -r["peak_bytes"])[:4]
        sec = {"estimate_peak_bytes": self.estimate_peak_bytes(),
               "programs": [{"name": r["name"], "source": r["source"],
                             "peak_bytes": r["peak_bytes"],
                             "param_bytes": r["param_bytes"]}
                            for r in progs]}
        if self._last_live is not None:
            sec["devices"] = self._last_live
            sec["devices_as_of"] = ("last-probe cache; crash dumps never "
                                    "touch the backend")
        return sec

    def clear(self):
        with self._lock:
            self._programs.clear()
            self._segment_peak = 0.0
            # drop the live-probe cache too: after a reset_all a later
            # flight dump must not attribute PRE-reset device bytes, and
            # live_available() must re-probe rather than answer stale
            self._last_live = None
            self._live_probed = False
            self._live_available = False


_observatory = MemoryObservatory()


def get_observatory() -> MemoryObservatory:
    return _observatory


def note_program(program, feed_arrays, source="executor", name=None):
    return _observatory.note_program(program, feed_arrays, source=source,
                                     name=name)


def report() -> dict:
    return _observatory.report()


def reset():
    _observatory.clear()
