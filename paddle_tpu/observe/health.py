"""fluid-pulse: metric time-series + online anomaly detection.

Rounds 8/11 made incidents readable after the fact; this module makes a
RUNNING process able to say "this run is going wrong" — the TF system
paper's per-task health story. Three pieces:

- ``TimeSeries``: a bounded ring of (ts, value) points, O(1) append,
  fed either directly (``feed``) or by riding the metrics registry's
  write path (``Registry.watch`` — counters contribute increments,
  gauges levels, histograms samples), enabling rates and derivatives
  without a second collection pipeline.

- Detectors: small stateful rules evaluated on demand (every /healthz
  or /status scrape, plus the pulse ticker) that flip between ok and
  firing. The built-in catalog (``install_default_detectors``):

  * ``non_finite_loss``      any non-finite point on the loss series
                             (sticky — NaN params don't self-heal)
  * ``grad_norm_spike``      latest grad norm above rolling
                             median + k*MAD of the trailing window
  * ``throughput_collapse``  recent step rate below a fraction of the
                             trailing-window rate
  * ``steady_state_recompile`` an unexpected observatory cause (not
                             warmup/first_call) after the grace steps
  * ``serve_queue_saturation`` queue depth >= 90% of capacity
  * ``kv_cache_exhaustion``  paged KV blocks (allocated + reserved)
                             >= 90% of capacity — generative admissions
                             are about to start bouncing
  * ``serve_deadline_miss``  deadline rejections above a windowed rate
  * ``ps_retry_storm``       client RPC retries above a windowed rate
  * ``lease_churn``          evictions+readmissions above a windowed rate
  * ``fleet_failover_storm`` router request failovers above a windowed
                             rate — replica membership is flapping
  * ``ps_replication_stall`` fluid-haven: the replication lag grows
                             monotonically over a window while pushes
                             keep landing — the backup stopped
                             keeping up (self-clears when the ack
                             watermark moves again)
  * ``quorum_loss``          fluid-quorum: a HELD lease cannot renew
                             against a strict majority of arbiters —
                             this holder is fenced (writes held) and
                             will step down at local expiry unless the
                             quorum comes back (self-clears on re-grant
                             or successful renew)
  * ``task_starvation``      fluid-elastic: the data master holds
                             outstanding tasks but no issue/finish
                             progress landed for a window — the data
                             plane is starved (self-clears)
  * ``task_discard``         fluid-elastic: a task burned its failure
                             budget and was discarded — its records
                             are silently lost for the pass (sticky)
  * ``wire_compression_collapse`` on-wire ratio fell to half of the
                             session's established ratio

- ``Alert``: a structured event fired ONCE per ok->firing transition —
  counted in the metrics registry (``health_alerts_total{rule=...}``)
  and recorded into the flight-recorder ring WITH the last points of
  the triggering series, so a postmortem dump shows why health went red
  before the crash.

Everything here is pull-evaluated and rides existing emit paths: with
the `observe` flag off nothing feeds the rings and nothing evaluates,
so the hot path stays at its zero-write contract.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from . import flight as _flight
from . import metrics as _metrics

DEFAULT_SERIES_POINTS = 512
ALERTS_METRIC = "health_alerts_total"


class TimeSeries:
    """Bounded (ts, value) ring with the derived views detectors need."""

    def __init__(self, capacity: int = DEFAULT_SERIES_POINTS):
        self._points: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()

    def append(self, value: float, ts: Optional[float] = None):
        with self._lock:
            self._points.append((time.time() if ts is None else ts,
                                 float(value)))

    def points(self, n: Optional[int] = None) -> List[Tuple[float, float]]:
        with self._lock:
            pts = list(self._points)
        return pts if n is None else pts[-n:]

    def values(self, n: Optional[int] = None) -> List[float]:
        return [v for _, v in self.points(n)]

    def last(self) -> Optional[Tuple[float, float]]:
        with self._lock:
            return self._points[-1] if self._points else None

    def __len__(self):
        with self._lock:
            return len(self._points)

    def window_sum(self, window_s: float, now: Optional[float] = None,
                   end_offset_s: float = 0.0) -> Tuple[float, int]:
        """(sum, count) of points with ts in
        [now - end_offset - window, now - end_offset]."""
        now = time.time() if now is None else now
        hi = now - end_offset_s
        lo = hi - window_s
        s, n = 0.0, 0
        for ts, v in self.points():
            if lo < ts <= hi:
                s += v
                n += 1
        return s, n

    def rate(self, window_s: float, now: Optional[float] = None,
             end_offset_s: float = 0.0) -> float:
        """Sum of values in the window divided by the window — the
        events/sec (or units/sec) of an increment-fed series."""
        s, _ = self.window_sum(window_s, now=now, end_offset_s=end_offset_s)
        return s / max(window_s, 1e-9)

    def derivative(self) -> Optional[float]:
        """d(value)/dt across the last two points (level-fed series)."""
        pts = self.points(2)
        if len(pts) < 2:
            return None
        (t0, v0), (t1, v1) = pts
        if t1 <= t0:
            return None
        return (v1 - v0) / (t1 - t0)


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


class Alert:
    """One fired health rule: what tripped, on what evidence."""

    __slots__ = ("rule", "metric", "observed", "threshold", "message",
                 "ts", "detail")

    def __init__(self, rule: str, metric: str, observed, threshold,
                 message: str, detail: Optional[dict] = None):
        self.rule = rule
        self.metric = metric
        self.observed = observed
        self.threshold = threshold
        self.message = message
        self.ts = time.time()
        self.detail = detail or {}

    def as_dict(self) -> dict:
        return {"rule": self.rule, "metric": self.metric,
                "observed": self.observed, "threshold": self.threshold,
                "message": self.message, "ts": self.ts,
                "detail": self.detail}

    def __repr__(self):
        return f"Alert({self.rule}: {self.message})"


class Detector:
    """Base rule: subclasses implement check() and call fire()/clear().
    `series` names the TimeSeries whose recent points ride along in the
    alert's flight-recorder record."""

    name = "detector"
    series: Optional[str] = None

    def check(self, engine: "HealthEngine", now: float) -> None:
        raise NotImplementedError

    def acknowledge(self, engine: "HealthEngine") -> None:
        """Operator remediation hook (engine.clear_alerts): a STICKY
        detector must re-baseline here so the cleared alert does not
        re-fire from the same old evidence on the next evaluate.
        Self-clearing detectors need nothing."""

    def state(self, engine: "HealthEngine") -> dict:
        """Introspection for /healthz check detail."""
        a = engine.active_alert(self.name)
        return {"firing": a is not None,
                **({"alert": a.as_dict()} if a else {})}


class NonFiniteDetector(Detector):
    """Any non-finite point on the series. STICKY: a NaN loss means the
    parameters are (or are about to be) poisoned — the alert never
    self-heals; after remediation an operator clears it with
    `engine.clear_alerts()` (or a full reset)."""

    def __init__(self, name: str = "non_finite_loss",
                 series: str = "train_loss"):
        self.name = name
        self.series = series
        # points at or before this ts are acknowledged history: after an
        # operator clear_alerts() the old NaN still on the ring must not
        # re-fire; only a NEW non-finite point is a new incident
        self._ack_ts = float("-inf")

    def check(self, engine, now):
        if engine.active_alert(self.name) is not None:
            return  # sticky
        ts = engine.series(self.series)
        for pt_ts, v in ts.points():
            if pt_ts <= self._ack_ts:
                continue
            if not math.isfinite(v):
                engine.fire(self, observed=v, threshold="finite",
                            message=f"non-finite value {v!r} on "
                                    f"{self.series}")
                return

    def acknowledge(self, engine):
        self._ack_ts = time.time()


class SpikeDetector(Detector):
    """Latest point above rolling median + k*MAD of the trailing window
    (robust z-score — one outlier in the history can't move the
    threshold much). Clears when the latest point is back under."""

    def __init__(self, name: str = "grad_norm_spike",
                 series: str = "grad_norm", window: int = 64,
                 k: float = 10.0, min_points: int = 8):
        self.name = name
        self.series = series
        self.window = window
        self.k = k
        self.min_points = min_points

    def check(self, engine, now):
        vals = engine.series(self.series).values(self.window + 1)
        if len(vals) < self.min_points:
            engine.clear(self)
            return
        cur, hist = vals[-1], vals[:-1]
        med = _median(hist)
        mad = _median([abs(v - med) for v in hist])
        # floor: a perfectly flat history has MAD 0 and any jitter would
        # fire — require at least a few percent of the median as spread
        thr = med + self.k * max(mad, 0.02 * abs(med), 1e-12)
        if math.isfinite(cur) and cur > thr:
            engine.fire(self, observed=cur, threshold=thr,
                        message=f"{self.series} {cur:.4g} above rolling "
                                f"median {med:.4g} + {self.k}*MAD")
        else:
            engine.clear(self)


class RateCollapseDetector(Detector):
    """Recent-window rate below `frac` of the trailing-window rate —
    throughput collapsed vs what this process was just sustaining.
    Needs a real trailing rate (min_trailing events) so an idle or
    just-started process never fires."""

    def __init__(self, name: str = "throughput_collapse",
                 series: str = "steps", recent_s: float = 5.0,
                 trailing_s: float = 30.0, frac: float = 0.25,
                 min_trailing: int = 20):
        self.name = name
        self.series = series
        self.recent_s = recent_s
        self.trailing_s = trailing_s
        self.frac = frac
        self.min_trailing = min_trailing

    def check(self, engine, now):
        ts = engine.series(self.series)
        pts = ts.points()
        if not pts:
            engine.clear(self)
            return
        # rates over the COVERED span only: a fast process wraps the
        # bounded ring in seconds, and dividing its partial window by
        # the full trailing_s would deflate the trailing rate and mask a
        # real collapse
        oldest = pts[0][0]
        recent_cov = max(min(self.recent_s, now - oldest), 1e-9)
        recent_sum, _ = ts.window_sum(self.recent_s, now=now)
        recent = recent_sum / recent_cov
        trail_hi = now - self.recent_s
        trail_cov = trail_hi - max(trail_hi - self.trailing_s, oldest)
        trail_sum, trail_n = ts.window_sum(self.trailing_s, now=now,
                                           end_offset_s=self.recent_s)
        if trail_n < self.min_trailing or trail_cov <= 0:
            # not enough trailing evidence to JUDGE — but a hang that
            # merely outlasts the trailing window is not recovery: while
            # firing, only actual steps in the recent window clear it
            if engine.active_alert(self.name) is None or recent > 0:
                engine.clear(self)
            return
        trailing = trail_sum / trail_cov
        if recent < self.frac * trailing:
            engine.fire(self, observed=round(recent, 3),
                        threshold=round(self.frac * trailing, 3),
                        message=f"{self.series} rate {recent:.2f}/s fell "
                                f"below {self.frac:.0%} of trailing "
                                f"{trailing:.2f}/s")
        else:
            engine.clear(self)


class RateSpikeDetector(Detector):
    """Windowed event count at or above a threshold (retry storms,
    deadline-miss bursts, lease churn). Clears when the window drains."""

    def __init__(self, name: str, series: str, window_s: float = 15.0,
                 threshold: float = 8.0):
        self.name = name
        self.series = series
        self.window_s = window_s
        self.threshold = threshold

    def check(self, engine, now):
        s, _ = engine.series(self.series).window_sum(self.window_s, now=now)
        if s >= self.threshold:
            engine.fire(self, observed=s, threshold=self.threshold,
                        message=f"{s:.0f} {self.series} events in "
                                f"{self.window_s:.0f}s (threshold "
                                f"{self.threshold:.0f})")
        else:
            engine.clear(self)


class RecompileDetector(Detector):
    """Steady-state recompile: an observatory event whose cause is not
    warmup/first_call AFTER the process has run `grace_steps` steps.
    STICKY — a recompiling steady state is a misconfiguration (mis-sized
    bucket ladder, mutating program) that won't heal on its own.

    Counts via the CUMULATIVE `executor_recompiles_total` metric, not
    the observatory's bounded event ring — ring eviction on a busy
    server would silently deflate a ring-length baseline and blind the
    detector (steplog.counts() documents exactly this hazard)."""

    name = "steady_state_recompile"
    series = None

    def __init__(self, grace_steps: int = 20):
        self.grace_steps = grace_steps
        self._baseline: Optional[float] = None

    @staticmethod
    def _unexpected_total() -> float:
        from .steplog import EXPECTED_CAUSES
        c = _metrics.default_registry().get("executor_recompiles_total")
        total = 0.0
        if c is not None:
            for labels, v in c.items():
                if labels.get("cause") not in EXPECTED_CAUSES:
                    total += v
        return total

    def check(self, engine, now):
        if engine.active_alert(self.name) is not None:
            return  # sticky
        from . import steplog as _steplog
        steps = _steplog.get_steplog().phase_summary()["steps"]
        total = self._unexpected_total()
        if self._baseline is None or steps <= self.grace_steps \
                or total < self._baseline:
            # warmup era — or the FIRST check of a health plane armed
            # mid-run (pre-pulse recompiles must not trip a permanent
            # sticky alert) — or a registry reset zeroed the counter:
            # re-baseline; only growth from here on is steady-state
            self._baseline = total
            return
        if total > self._baseline:
            unexpected = _steplog.observatory().unexpected()
            ev = unexpected[-1] if unexpected else None
            engine.fire(self, observed=ev.cause if ev else "unknown",
                        threshold=f"none after step {self.grace_steps}",
                        message=f"steady-state recompile: cause="
                                f"{ev.cause if ev else '?'} source="
                                f"{ev.source if ev else '?'} after "
                                f"{steps} steps "
                                f"({total - self._baseline:.0f} new)")

    def acknowledge(self, engine):
        # remediated: the counted recompiles become history; only NEW
        # growth fires again
        self._baseline = self._unexpected_total()


# ONE definition of "saturated" for the whole plane: the detector and
# the InferenceServer's registered /readyz check both read this, so the
# two verdicts in one /healthz body can never use divergent thresholds
SERVE_QUEUE_SATURATION_FRAC = 0.9

# ONE definition of "nearly exhausted" shared by the detector and any
# serving-side check, mirroring SERVE_QUEUE_SATURATION_FRAC's contract
KV_CACHE_EXHAUSTION_FRAC = 0.9


class CapacityRatioDetector(Detector):
    """Shared shape of every used-vs-capacity rule: a pair of gauges
    with matching label sets; fire when ANY label's used >= frac *
    capacity, clear when none is. `message_fmt` may reference {model},
    {used}, {cap} and {frac}."""

    series = None

    def __init__(self, name: str, used_metric: str, capacity_metric: str,
                 frac: float, message_fmt: str):
        self.name = name
        self.used_metric = used_metric
        self.capacity_metric = capacity_metric
        self.frac = frac
        self.message_fmt = message_fmt

    def check(self, engine, now):
        reg = _metrics.default_registry()
        used = reg.get(self.used_metric)
        cap = reg.get(self.capacity_metric)
        if used is None or cap is None:
            engine.clear(self)
            return
        caps = {tuple(sorted(labels.items())): v for labels, v in cap.items()}
        for labels, u in used.items():
            c = caps.get(tuple(sorted(labels.items())))
            if c and u >= self.frac * c:
                engine.fire(self, observed=u, threshold=self.frac * c,
                            message=self.message_fmt.format(
                                model=labels.get("model", "?"), used=u,
                                cap=c, frac=self.frac))
                return
        engine.clear(self)


class QueueSaturationDetector(CapacityRatioDetector):
    """serve_queue_depth at or above `frac` of serve_queue_capacity for
    any model label (both gauges are set by the MicroBatcher)."""

    def __init__(self, frac: float = SERVE_QUEUE_SATURATION_FRAC):
        super().__init__(
            "serve_queue_saturation", "serve_queue_depth",
            "serve_queue_capacity", frac,
            "serve queue {model} at {used:.0f}/{cap:.0f} (>= {frac:.0%})")


class KvCacheExhaustionDetector(CapacityRatioDetector):
    """fluid-decode: paged-KV occupancy (allocated + admission-reserved
    blocks, i.e. exactly what the admission check sees) at or above
    `frac` of capacity for any (model, version) label. Fires BEFORE
    admissions start failing with CacheExhaustedError — the
    router/operator signal to shed generative load or grow the cache.
    Self-clears as finished sequences free their blocks."""

    def __init__(self, frac: float = KV_CACHE_EXHAUSTION_FRAC):
        super().__init__(
            "kv_cache_exhaustion", "serve_kv_blocks_in_use",
            "serve_kv_blocks_capacity", frac,
            "KV cache {model} at {used:.0f}/{cap:.0f} blocks "
            "(>= {frac:.0%}) — generative admissions about to stall")


class ReplicationStallDetector(Detector):
    """fluid-haven: the primary's replication lag
    (`ps_replication_lag_updates` gauge, fed from the ack watermark)
    grew MONOTONICALLY across the window while pushes kept being served
    — the backup is alive enough to hold the connection but not keeping
    up, so the failover loss bound is eroding toward the full window.
    Idle lag (no pushes) never fires: a paused trainer is not a stall.
    Self-clears as soon as the watermark catches up (lag dips)."""

    name = "ps_replication_stall"
    series = "ps_replication_lag"

    def __init__(self, window_s: float = 20.0, min_points: int = 4):
        self.window_s = window_s
        self.min_points = min_points

    def check(self, engine, now):
        pts = [(ts, v) for ts, v in engine.series(self.series).points()
               if ts > now - self.window_s]
        if len(pts) < self.min_points:
            engine.clear(self)
            return
        vals = [v for _ts, v in pts]
        growing = all(b >= a for a, b in zip(vals, vals[1:])) \
            and vals[-1] > vals[0] and vals[-1] > 0
        pushes, _n = engine.series("ps_push_serves").window_sum(
            self.window_s, now=now)
        if growing and pushes > 0:
            engine.fire(self, observed=vals[-1], threshold=vals[0],
                        message=f"replication lag grew {vals[0]:.0f} -> "
                                f"{vals[-1]:.0f} updates over "
                                f"{self.window_s:.0f}s while "
                                f"{pushes:.0f} pushes landed — backup "
                                f"not keeping up")
        else:
            engine.clear(self)


class QuorumLossDetector(Detector):
    """fluid-quorum: any resource whose `quorum_lease_ok` gauge sits at
    0 — the holder believes it owns the lease but its renew rounds
    cannot reach a strict majority of arbiters. While this fires the
    holder's write path is fenced; if it persists to local expiry the
    holder steps down. Self-clears the moment a renew or a fresh grant
    lands (the client writes the gauge back to 1)."""

    name = "quorum_loss"
    series = "quorum_lease_ok"

    def check(self, engine, now):
        reg = _metrics.default_registry()
        g = reg.get("quorum_lease_ok")
        if g is None:
            engine.clear(self)
            return
        for labels, v in g.items():
            if v == 0.0:
                engine.fire(
                    self, observed=0.0, threshold=1.0,
                    message=f"quorum lease "
                            f"{labels.get('resource', '?')!r} cannot "
                            f"renew against a majority — holder fenced, "
                            f"step-down at local expiry",
                    detail=dict(labels))
                return
        engine.clear(self)


class TaskStarvationDetector(Detector):
    """fluid-elastic: the data master holds outstanding work (todo +
    pending gauges > 0) but NO task has been issued or finished for a
    window — trainers stopped pulling (all dead? all wedged on a fenced
    master?) or the master stopped issuing. Requires the progress
    series to have EVER moved, so a freshly loaded dataset whose
    trainers simply haven't started yet never fires. Self-clears on the
    next issue/finish."""

    name = "task_starvation"
    series = "master_task_progress"

    def __init__(self, window_s: float = 15.0):
        self.window_s = window_s

    def check(self, engine, now):
        reg = _metrics.default_registry()
        outstanding = 0.0
        for metric in ("master_tasks_todo", "master_tasks_pending"):
            g = reg.get(metric)
            if g is not None:
                outstanding += sum(v for _l, v in g.items())
        ts = engine.series(self.series)
        if outstanding <= 0 or len(ts) == 0:
            engine.clear(self)
            return
        s, _n = ts.window_sum(self.window_s, now=now)
        if s == 0:
            engine.fire(self, observed=outstanding, threshold=0,
                        message=f"{outstanding:.0f} tasks outstanding but "
                                f"no issue/finish progress in "
                                f"{self.window_s:.0f}s — the data plane "
                                f"is starved")
        else:
            engine.clear(self)


class TaskDiscardDetector(Detector):
    """fluid-elastic: a task burned its failure budget and was DISCARDED
    — every record it carried is silently lost for this pass (today's
    quiet data-loss mode, reference processFailedTask :323). STICKY:
    lost data does not come back; after remediation (re-run the pass)
    an operator clears it with `engine.clear_alerts()`. Discards that
    pre-date the health plane arming are baselined, not alerted."""

    name = "task_discard"
    series = "master_task_discards"

    def __init__(self):
        self._baseline: Optional[float] = None

    def check(self, engine, now):
        if engine.active_alert(self.name) is not None:
            return  # sticky
        c = _metrics.default_registry().get("master_tasks_discarded_total")
        total = c.total() if c is not None else 0.0
        if self._baseline is None or total < self._baseline:
            # first check of a plane armed mid-run, or a registry reset
            self._baseline = total
            return
        if total > self._baseline:
            engine.fire(self, observed=total,
                        threshold=self._baseline,
                        message=f"{total - self._baseline:.0f} task(s) "
                                f"discarded after burning their failure "
                                f"budget — their records are LOST for "
                                f"this pass")

    def acknowledge(self, engine):
        c = _metrics.default_registry().get("master_tasks_discarded_total")
        self._baseline = c.total() if c is not None else 0.0


class CompressionCollapseDetector(Detector):
    """fluid-wire ratio collapse: the windowed raw/on-wire byte ratio
    fell to half of the best ratio this session established. A session
    that never compressed (raw mode, ratio ~1) never fires."""

    name = "wire_compression_collapse"
    series = "wire_encoded_bytes"

    def __init__(self, window_s: float = 30.0, min_bytes: float = 4096.0,
                 established: float = 1.5, collapse_frac: float = 0.5):
        self.window_s = window_s
        self.min_bytes = min_bytes
        self.established = established
        self.collapse_frac = collapse_frac
        self._best = 0.0

    def check(self, engine, now):
        raw, _ = engine.series("wire_raw_bytes").window_sum(self.window_s,
                                                            now=now)
        enc, _ = engine.series("wire_encoded_bytes").window_sum(
            self.window_s, now=now)
        if enc < self.min_bytes or raw <= 0:
            engine.clear(self)
            return
        ratio = raw / enc
        self._best = max(self._best, ratio)
        if self._best >= self.established and \
                ratio < self.collapse_frac * self._best:
            engine.fire(self, observed=round(ratio, 2),
                        threshold=round(self.collapse_frac * self._best, 2),
                        message=f"wire compression fell to {ratio:.2f}x "
                                f"(session best {self._best:.2f}x) — "
                                f"quantization silently degraded?")
        else:
            engine.clear(self)


# default metric -> series plumbing: which registry writes feed which ring
DEFAULT_WATCHES = (
    # (metric name, series name, label filter or None)
    ("trainer_last_loss", "train_loss", None),
    ("trainer_grad_norm", "grad_norm", None),
    ("executor_steps_total", "steps", None),
    ("pserver_client_retries_total", "ps_retries", None),
    ("pserver_trainers_evicted_total", "lease_churn", None),
    ("pserver_trainers_readmitted_total", "lease_churn", None),
    ("serve_rejects_total", "serve_deadline_miss", {"reason": "deadline"}),
    ("pserver_wire_bytes_raw", "wire_raw_bytes", None),
    ("pserver_wire_bytes_encoded", "wire_encoded_bytes", None),
    # fluid-fleet: router-side failovers (a replica answered a request
    # another replica dropped) — a storm means replicas are flapping
    ("fleet_failovers_total", "fleet_failovers", None),
    # fluid-haven: replication lag levels (gauge) + the push traffic
    # that distinguishes a stalling backup from an idle trainer — one
    # spec per push command (the watch filter is exact-match)
    ("ps_replication_lag_updates", "ps_replication_lag", None),
    ("pserver_server_requests_total", "ps_push_serves",
     {"cmd": "push_grad"}),
    ("pserver_server_requests_total", "ps_push_serves",
     {"cmd": "push_grads"}),
    ("pserver_server_requests_total", "ps_push_serves",
     {"cmd": "push_grads_sync"}),
    ("pserver_server_requests_total", "ps_push_serves",
     {"cmd": "push_sparse_grad"}),
    # fluid-quorum: renew verdicts (1 ok / 0 failing while held) — the
    # quorum_loss detector's evidence series for alert postmortems
    ("quorum_lease_ok", "quorum_lease_ok", None),
    # fluid-elastic: master task-lifecycle progress (issues + finishes
    # both count — either proves the data plane is moving) and the
    # discard stream the task_discard detector baselines against
    ("master_tasks_issued_total", "master_task_progress", None),
    ("master_tasks_finished_total", "master_task_progress", None),
    ("master_tasks_discarded_total", "master_task_discards", None),
)


class HealthEngine:
    """Series store + detector set + external checks -> one verdict."""

    def __init__(self):
        self._lock = threading.RLock()
        self._series: Dict[str, TimeSeries] = {}
        self._detectors: Dict[str, Detector] = {}
        self._active: Dict[str, Alert] = {}
        self._history: deque = deque(maxlen=128)
        self._checks: Dict[str, Tuple[Callable, bool]] = {}
        # each spec is [metric, series, label_filter, armed_generation]:
        # a sink is (re-)registered only when the spec's armed generation
        # differs from the registry's — arming exactly once per
        # generation, so a spec can never double-feed its ring (a doubled
        # series would fire windowed detectors at half their threshold)
        self._watch_specs: List[list] = []
        # (metric, sink) pairs currently registered with the registry, so
        # reset() can DETACH them — an orphaned sink would keep feeding a
        # dead ring on every metric write
        self._armed_sinks: List[Tuple[str, Callable]] = []
        self._defaults_installed = False

    # -- series -----------------------------------------------------------

    def series(self, name: str) -> TimeSeries:
        with self._lock:
            ts = self._series.get(name)
            if ts is None:
                ts = self._series[name] = TimeSeries()
            return ts

    def feed(self, name: str, value: float, ts: Optional[float] = None):
        """Direct append (callers that hold a value but no metric)."""
        self.series(name).append(value, ts=ts)

    def watch_metric(self, metric: str, series: Optional[str] = None,
                     label_filter: Optional[dict] = None):
        """Feed `series` from every write of registry metric `metric`
        (optionally only writes whose labels match `label_filter`).
        Survives registry resets: the watch re-arms on the next
        evaluate()."""
        with self._lock:
            self._watch_specs.append([metric, series or metric,
                                      label_filter, None])
        self._ensure_watches()

    def _arm(self, spec):
        metric, series_name, label_filter, _ = spec
        ring = self.series(series_name)

        def sink(value, label_key):
            if label_filter:
                d = dict(label_key)
                for lk, lv in label_filter.items():
                    if d.get(lk) != str(lv):
                        return
            ring.append(value)

        # stamp the generation the sink was actually registered INTO
        # (returned under the registry lock): a reset racing this arm
        # either clears the sink (stamp stays stale -> re-armed next
        # check) or post-dates it (stamp is current) — never two live
        # sinks for one spec
        spec[3] = _metrics.default_registry().watch(metric, sink)
        self._armed_sinks.append((metric, sink))

    def _ensure_watches(self):
        gen = _metrics.default_registry().generation()
        with self._lock:
            for spec in self._watch_specs:
                if spec[3] != gen:
                    self._arm(spec)

    # -- detectors / checks ----------------------------------------------

    def add_detector(self, det: Detector):
        with self._lock:
            self._detectors[det.name] = det

    def install_default_detectors(self):
        """The built-in catalog + its metric->series plumbing. Idempotent
        — start_pulse() calls this so a bare `observe.start_pulse()` is a
        fully armed health plane."""
        with self._lock:
            if self._defaults_installed:
                return
            self._defaults_installed = True
            specs = [s for s in DEFAULT_WATCHES
                     if not any(w[0] == s[0] and w[1] == s[1]
                                for w in self._watch_specs)]
            for metric, series_name, label_filter in specs:
                self._watch_specs.append([metric, series_name,
                                          label_filter, None])
        for det in (NonFiniteDetector(),
                    # a poisoned PARAMETER shows up as a non-finite
                    # gradient norm on the next step — this is the
                    # "non-finite param" leg of the catalog
                    NonFiniteDetector(name="non_finite_grad",
                                      series="grad_norm"),
                    SpikeDetector(),
                    RateCollapseDetector(),
                    RecompileDetector(),
                    QueueSaturationDetector(),
                    KvCacheExhaustionDetector(),
                    RateSpikeDetector("ps_retry_storm", "ps_retries",
                                      window_s=15.0, threshold=8.0),
                    RateSpikeDetector("lease_churn", "lease_churn",
                                      window_s=60.0, threshold=3.0),
                    RateSpikeDetector("serve_deadline_miss",
                                      "serve_deadline_miss",
                                      window_s=15.0, threshold=8.0),
                    # fluid-fleet: sustained request rerouting — one
                    # failover per dead replica is the design working;
                    # a windowed burst means membership is flapping or a
                    # replica is half-dead (accepting then dropping)
                    RateSpikeDetector("fleet_failover_storm",
                                      "fleet_failovers",
                                      window_s=15.0, threshold=8.0),
                    ReplicationStallDetector(),
                    QuorumLossDetector(),
                    TaskStarvationDetector(),
                    TaskDiscardDetector(),
                    CompressionCollapseDetector()):
            self.add_detector(det)
        self._ensure_watches()   # arms only the not-yet-armed specs

    def register_check(self, name: str, fn: Callable, ready: bool = True):
        """External component check: `fn() -> (ok, detail_dict)`.
        `ready=True` checks also gate /readyz (the fluid-fleet router's
        take-traffic signal)."""
        with self._lock:
            self._checks[name] = (fn, ready)

    def unregister_check(self, name: str):
        with self._lock:
            self._checks.pop(name, None)

    # -- evaluation -------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> List[Alert]:
        """Run every detector once; returns the active alerts."""
        self._ensure_watches()
        now = time.time() if now is None else now
        with self._lock:
            dets = list(self._detectors.values())
        for det in dets:
            try:
                det.check(self, now)
            except Exception:
                pass  # one broken rule must not take down the verdict
        with self._lock:
            return list(self._active.values())

    def fire(self, det: Detector, observed, threshold, message: str,
             detail: Optional[dict] = None):
        """ok -> firing transition: record once; re-fires while already
        active only refresh the observed value."""
        with self._lock:
            existing = self._active.get(det.name)
            if existing is not None:
                existing.observed = observed
                return
            alert = Alert(det.name, det.series or det.name, observed,
                          threshold, message, detail)
            self._active[det.name] = alert
            self._history.append(alert)
        _metrics.counter(
            ALERTS_METRIC, "health detector alerts fired").inc(
                rule=det.name)
        # flight recorder: the alert AND the last points of the
        # triggering series, so the postmortem shows why health went red
        points = []
        if det.series is not None:
            points = [(round(ts, 3), v)
                      for ts, v in self.series(det.series).points(16)]
        _flight.note("alert", rule=det.name, metric=alert.metric,
                     threshold=threshold, observed=observed,
                     message=message, points=points)

    def clear(self, det: Detector):
        with self._lock:
            alert = self._active.pop(det.name, None)
        if alert is not None:
            _flight.note("alert_clear", rule=det.name)

    def active_alert(self, rule: str) -> Optional[Alert]:
        with self._lock:
            return self._active.get(rule)

    def active_alerts(self) -> List[Alert]:
        with self._lock:
            return list(self._active.values())

    def history(self) -> List[Alert]:
        with self._lock:
            return list(self._history)

    # -- verdict (the /healthz /readyz JSON) ------------------------------

    def verdict(self, ready_only: bool = False) -> dict:
        """The health-plane contract (docs/OBSERVABILITY.md §fluid-pulse):
        ``status`` is "ok" or "unready"; every check contributes
        ``{ok, detail}``; active alerts ride along in full."""
        import os

        from . import xray as _xray

        alerts = self.evaluate()
        checks: Dict[str, dict] = {}
        with self._lock:
            ext = dict(self._checks)
            dets = list(self._detectors.values())
        for name, (fn, ready) in ext.items():
            if ready_only and not ready:
                continue
            try:
                ok, detail = fn()
            except Exception as e:
                ok, detail = False, {"error": f"{type(e).__name__}: {e}"}
            checks[name] = {"ok": bool(ok), "detail": detail}
        checks["detectors"] = {
            "ok": not alerts,
            "detail": {d.name: d.state(self) for d in dets}}
        ok_all = all(c["ok"] for c in checks.values())
        return {
            "status": "ok" if ok_all else "unready",
            "ts": time.time(),
            "pid": os.getpid(),
            "process": _xray.process_name(),
            "checks": checks,
            "alerts": [a.as_dict() for a in alerts],
        }

    def clear_alerts(self):
        """Operator path for clearing STICKY alerts (non-finite,
        steady-state recompile) after remediation — wiring stays intact,
        unlike reset(). Each cleared rule's detector is acknowledged so
        the SAME old evidence (the NaN still on the ring, the already-
        counted recompiles) cannot re-fire it on the next evaluate;
        fresh evidence fires a fresh alert."""
        with self._lock:
            rules = list(self._active)
            dets = dict(self._detectors)
            self._active.clear()
        for rule in rules:
            det = dets.get(rule)
            if det is not None:
                try:
                    det.acknowledge(self)
                except Exception:
                    pass

    def reset(self):
        with self._lock:
            # detach armed sinks: registry watches would otherwise keep
            # feeding orphaned rings on every write (and accumulate one
            # closure per reset/install cycle)
            reg = _metrics.default_registry()
            for metric, sink in self._armed_sinks:
                reg.unwatch(metric, sink)
            self._armed_sinks.clear()
            self._series.clear()
            self._detectors.clear()
            self._active.clear()
            self._history.clear()
            self._checks.clear()
            self._watch_specs.clear()
            self._defaults_installed = False


_engine = HealthEngine()


def get_engine() -> HealthEngine:
    return _engine


def note_loss_fetch(outs) -> None:
    """Land a fetched loss on the health plane: sets the
    `trainer_last_loss` gauge (the emit path DEFAULT_WATCHES mirrors
    into the `train_loss` series the non-finite detector scans). ONE
    definition shared by Trainer and the PS trainers — the detector
    keys on this exact metric name. Caller gates on the observe flag.

    `outs` is the step's user fetch list. By fluid convention the loss
    is fetch[0] and that value feeds the series — but a NON-FINITE
    scalar anywhere in the fetches overrides it, so a caller who
    ordered fetch_list=[acc, loss] still trips the non-finite detector
    when the loss goes NaN (any poisoned training scalar is the signal,
    whatever its slot)."""
    import math

    import numpy as np
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    val = None
    for i, o in enumerate(outs):
        v = np.asarray(o)
        if v.size != 1:
            continue
        f = float(v.reshape(-1)[0])
        if val is None and i == 0:
            val = f
        if not math.isfinite(f):
            val = f
            break
    if val is not None:
        _metrics.gauge("trainer_last_loss",
                       "most recent training loss (fetch[0]; any "
                       "non-finite scalar fetch overrides)").set(val)


def feed(name: str, value: float, ts: Optional[float] = None):
    _engine.feed(name, value, ts=ts)


def reset():
    """Clear the engine. If a pulse server is LIVE, the default
    detectors re-install immediately — a running health plane must not
    be left evaluating zero rules (it would answer a trivial 200 ok for
    the rest of the process lifetime). To clear sticky alerts after
    remediation, prefer `get_engine().clear_alerts()`."""
    _engine.reset()
    from . import pulse as _pulse   # lazy: pulse imports health
    if _pulse.get_pulse() is not None:
        _engine.install_default_detectors()
