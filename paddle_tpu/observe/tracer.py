"""Structured span tracer: a bounded ring buffer of timed events with
parent/child nesting and chrome://tracing JSON export.

Reference analog: platform/profiler.cc's RecordEvent host-event table +
tools/timeline.py's chrome-trace conversion, unified into one store. The
`paddle_tpu.profiler` module's `record_event` / `print_host_events` /
`export_chrome_tracing` API is now a thin veneer over this tracer, so
host annotations, executor step phases, trainer epoch marks and RPC spans
all land in ONE timeline.

The ring is bounded (default 16384 events): a week-long training run
cannot grow host memory through telemetry — old events fall off the back,
aggregate counts live in observe.metrics instead.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

DEFAULT_CAPACITY = 16384

# chrome-trace process identity (fluid-xray): exports carry the REAL pid
# plus a human process name as "M"-phase metadata, so per-process trace
# files from a distributed run merge into one timeline with each process
# on its own named track (tools/telemetry_dump.py --merge).
_process_name: Optional[str] = None


def set_process_name(name: str):
    global _process_name
    _process_name = str(name)


def get_process_name() -> str:
    if _process_name is not None:
        return _process_name
    return os.environ.get("PADDLE_TPU_PROC_NAME", f"pid{os.getpid()}")


class Span:
    """One completed timed event (chrome "X" phase)."""

    __slots__ = ("name", "cat", "ts", "dur", "tid", "depth", "args")

    def __init__(self, name: str, cat: str, ts: float, dur: float,
                 tid: int, depth: int = 0, args: Optional[dict] = None):
        self.name = name
        self.cat = cat
        self.ts = ts          # wall-clock seconds (time.time epoch)
        self.dur = dur        # seconds (perf_counter delta)
        self.tid = tid
        self.depth = depth
        self.args = args or {}

    def __repr__(self):
        return (f"Span({self.name!r}, cat={self.cat!r}, "
                f"dur={self.dur * 1e3:.3f}ms, depth={self.depth})")


class Tracer:
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        # tid -> thread name, captured at RECORD time: a batcher/conn
        # thread may be long gone by export time, and an unnamed track
        # defeats the merged timeline's readability
        self._tid_names: Dict[int, str] = {}

    @property
    def capacity(self) -> int:
        return self._events.maxlen

    def set_capacity(self, capacity: int):
        """Re-bound the ring, keeping the most recent events that fit."""
        with self._lock:
            self._events = deque(self._events, maxlen=capacity)

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host", **args):
        """Timed nested region. The event is recorded even when the body
        raises (the failing iteration is usually the one being profiled);
        nesting depth is tracked per thread and stored on the event."""
        stack = self._stack()
        depth = len(stack)
        stack.append(name)
        ts = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            stack.pop()
            self.record(name, ts, dur, cat=cat, depth=depth,
                        parent=stack[-1] if stack else None, **args)

    def record(self, name: str, ts: float, dur: float, cat: str = "host",
               tid: Optional[int] = None, depth: int = 0, parent=None,
               **args):
        """Append a completed span directly (for callers that timed the
        region themselves, e.g. the executor's phase timers)."""
        if parent is not None:
            args = dict(args, parent=parent)
        name_update = None
        if tid is None:
            tid = threading.get_ident()
            # unconditional refresh: idents recycle after a thread exits,
            # and a stale name on a recycled tid would mislabel the track
            name_update = threading.current_thread().name
        ev = Span(name, cat, ts, dur, tid, depth, args)
        with self._lock:
            if name_update is not None:
                self._tid_names[tid] = name_update
            self._events.append(ev)
        return ev

    def record_ctx(self, name: str, ts: float, dur: float, cat: str,
                   ctx, extra: dict):
        """Hot-path append for xray spans: the ring stores a raw tuple
        (no Span object, no trace-id formatting, no args-dict merge) and
        `events()` materializes it into a Span on read. The serve path
        records 2+ spans per request and exports ~never, so the horizon
        A/B prices exactly this deferral. `ctx` is an immutable
        SpanContext and `extra` is relinquished by the caller (stored,
        not copied)."""
        tid = threading.get_ident()
        with self._lock:
            if tid not in self._tid_names:
                # conditional (unlike `record`'s refresh): serve/RPC hot
                # threads are long-lived, and a recycled ident keeping a
                # dead thread's track name is cosmetic — not worth a
                # current_thread() lookup per request
                self._tid_names[tid] = threading.current_thread().name
            self._events.append((name, cat, ts, dur, tid, ctx, extra))

    @staticmethod
    def _materialize(ev) -> Span:
        if ev.__class__ is Span:
            return ev
        name, cat, ts, dur, tid, ctx, extra = ev
        args = ctx.trace_args()
        if extra:
            args.update(extra)
        return Span(name, cat, ts, dur, tid, 0, args)

    def events(self, cat: Optional[str] = None) -> List[Span]:
        with self._lock:
            evs = list(self._events)
        if cat is not None:
            return [e for e in map(self._materialize, evs) if e.cat == cat]
        return [self._materialize(e) for e in evs]

    def clear(self):
        with self._lock:
            self._events.clear()
            # thread names must reset with the events: a recycled thread
            # ident would otherwise label a later span's track with a
            # dead thread's name in the chrome export
            self._tid_names.clear()

    def __len__(self):
        with self._lock:
            return len(self._events)

    # -- aggregation (the reference DisableProfiler printed table) ---------
    def aggregate(self, cat: Optional[str] = None) -> Dict[str, list]:
        """name -> [calls, total_s, max_s, min_s] over recorded events."""
        agg: Dict[str, list] = {}
        for e in self.events(cat=cat):
            a = agg.setdefault(e.name, [0, 0.0, 0.0, float("inf")])
            a[0] += 1
            a[1] += e.dur
            a[2] = max(a[2], e.dur)
            a[3] = min(a[3], e.dur)
        return agg

    # -- chrome://tracing export -------------------------------------------
    def chrome_events(self, cat: Optional[str] = None) -> List[dict]:
        """Span ("X") events under this process's REAL pid, prefixed with
        "M" metadata naming the process and its threads — required for a
        multi-process merge to render as distinct named tracks."""
        pid = os.getpid()
        spans = []
        tids = set()
        for e in self.events(cat=cat):
            ev = {"name": e.name, "ph": "X", "pid": pid, "tid": e.tid,
                  "ts": int(e.ts * 1e6), "dur": int(e.dur * 1e6),
                  "cat": e.cat}
            if e.args or e.depth:
                ev["args"] = dict(e.args, depth=e.depth)
            spans.append(ev)
            tids.add(e.tid)
        meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                 "args": {"name": get_process_name()}}]
        # record-time names first (threads may have exited since), live
        # threads as a fallback for spans recorded with an explicit tid
        thread_names = {t.ident: t.name for t in threading.enumerate()
                        if t.ident is not None}
        with self._lock:   # a recording thread may be inserting a new tid
            thread_names.update(self._tid_names)
        for tid in sorted(tids):
            name = thread_names.get(tid)
            if name:
                meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                             "tid": tid, "args": {"name": name}})
        return meta + spans

    def export_chrome(self, path: str, cat: Optional[str] = None) -> str:
        """Write the ring as chrome://tracing JSON (reference
        tools/timeline.py emits the same schema from the profiler proto)."""
        with open(path, "w") as f:
            json.dump({"traceEvents": self.chrome_events(cat=cat),
                       "displayTimeUnit": "ms"}, f)
        return path


_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


# -- multi-process merge (fluid-xray) ---------------------------------------

def load_chrome_trace(path: str) -> dict:
    """Load one chrome-trace JSON file with a diagnosable failure mode:
    an empty or non-JSON file raises ValueError NAMING the file (a
    distributed drill merging N per-process dumps must say which worker
    produced the bad one, not surface a bare JSONDecodeError), as does a
    document without a `traceEvents` list."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError as e:
        raise ValueError(f"unreadable trace file {path!r}: {e}") from e
    if not text.strip():
        raise ValueError(f"empty trace file {path!r} (the producing "
                         "process likely died before export)")
    try:
        doc = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(f"malformed trace file {path!r}: {e}") from e
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        raise ValueError(f"trace file {path!r} has no traceEvents list")
    return doc


def merge_chrome_traces(paths: Sequence[str],
                        out_path: Optional[str] = None,
                        strict: bool = False
                        ) -> Tuple[dict, dict]:
    """Stitch per-process chrome-trace files into ONE timeline.

    Every "X" span of every input survives verbatim (the caller can —
    and chaos drills do — fail hard when `spans_out != spans_in`;
    `strict=True` makes the merge itself raise RuntimeError on that
    mismatch). Process identity is kept distinct: if two files claim
    the same pid but different process names (a restarted worker
    recycling a pid, or two single-process drills merged after the
    fact), the later file's events are remapped onto a fresh synthetic
    pid. Metadata ("M") events are deduplicated per (pid, name, tid).
    Empty or malformed input files raise ValueError naming the file
    (`load_chrome_trace`).

    Returns (merged_doc, stats) where stats carries per-file and total
    span counts; `out_path` additionally writes the merged JSON."""
    merged_meta: List[dict] = []
    merged_spans: List[dict] = []
    seen_meta = set()
    pid_owner: Dict[int, str] = {}      # pid -> process name that owns it
    used_pids = set()
    stats = {"files": {}, "spans_in": 0, "spans_out": 0, "processes": []}
    for path in paths:
        doc = load_chrome_trace(path)
        events = doc.get("traceEvents", [])
        # span budget counted straight off the LOADED file, independent
        # of the transform loop below — so the spans_out gate actually
        # catches a future merge change that filters events
        n_spans = sum(1 for ev in events if ev.get("ph") != "M")
        pname = None
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                pname = ev.get("args", {}).get("name")
                break
        pname = pname or os.path.basename(path)
        # pid remap when a pid is already owned by a DIFFERENT process
        remap: Dict[int, int] = {}

        def _pid_of(ev):
            pid = ev.get("pid", 0)
            if pid in remap:
                return remap[pid]
            owner = pid_owner.get(pid)
            if owner is not None and owner != pname:
                new = pid
                while new in used_pids:
                    new += 1 << 20
                remap[pid] = new
                used_pids.add(new)
                pid_owner[new] = pname
                return new
            pid_owner[pid] = pname
            used_pids.add(pid)
            return pid

        for ev in events:
            ev = dict(ev, pid=_pid_of(ev))
            if ev.get("ph") == "M":
                key = (ev["pid"], ev.get("name"), ev.get("tid"),
                       json.dumps(ev.get("args", {}), sort_keys=True))
                if key in seen_meta:
                    continue
                seen_meta.add(key)
                merged_meta.append(ev)
            else:
                merged_spans.append(ev)
        stats["files"][path] = {"process": pname, "spans": n_spans}
        stats["spans_in"] += n_spans
        if pname not in stats["processes"]:
            stats["processes"].append(pname)
    merged_spans.sort(key=lambda e: e.get("ts", 0))
    doc = {"traceEvents": merged_meta + merged_spans,
           "displayTimeUnit": "ms"}
    stats["spans_out"] = len(merged_spans)
    if strict and stats["spans_out"] != stats["spans_in"]:
        raise RuntimeError(
            f"merge dropped spans: {stats['spans_in']} in, "
            f"{stats['spans_out']} out across {len(list(paths))} files")
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump(doc, f)
    return doc, stats
