"""Structured span tracer: a bounded ring buffer of timed events with
parent/child nesting and chrome://tracing JSON export.

Reference analog: platform/profiler.cc's RecordEvent host-event table +
tools/timeline.py's chrome-trace conversion, unified into one store. The
`paddle_tpu.profiler` module's `record_event` / `print_host_events` /
`export_chrome_tracing` API is now a thin veneer over this tracer, so
host annotations, executor step phases, trainer epoch marks and RPC spans
all land in ONE timeline.

The ring is bounded (default 16384 events): a week-long training run
cannot grow host memory through telemetry — old events fall off the back,
aggregate counts live in observe.metrics instead.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from collections import deque
from typing import Dict, List, Optional

DEFAULT_CAPACITY = 16384


class Span:
    """One completed timed event (chrome "X" phase)."""

    __slots__ = ("name", "cat", "ts", "dur", "tid", "depth", "args")

    def __init__(self, name: str, cat: str, ts: float, dur: float,
                 tid: int, depth: int = 0, args: Optional[dict] = None):
        self.name = name
        self.cat = cat
        self.ts = ts          # wall-clock seconds (time.time epoch)
        self.dur = dur        # seconds (perf_counter delta)
        self.tid = tid
        self.depth = depth
        self.args = args or {}

    def __repr__(self):
        return (f"Span({self.name!r}, cat={self.cat!r}, "
                f"dur={self.dur * 1e3:.3f}ms, depth={self.depth})")


class Tracer:
    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._local = threading.local()

    @property
    def capacity(self) -> int:
        return self._events.maxlen

    def set_capacity(self, capacity: int):
        """Re-bound the ring, keeping the most recent events that fit."""
        with self._lock:
            self._events = deque(self._events, maxlen=capacity)

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "host", **args):
        """Timed nested region. The event is recorded even when the body
        raises (the failing iteration is usually the one being profiled);
        nesting depth is tracked per thread and stored on the event."""
        stack = self._stack()
        depth = len(stack)
        stack.append(name)
        ts = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            stack.pop()
            self.record(name, ts, dur, cat=cat, depth=depth,
                        parent=stack[-1] if stack else None, **args)

    def record(self, name: str, ts: float, dur: float, cat: str = "host",
               tid: Optional[int] = None, depth: int = 0, parent=None,
               **args):
        """Append a completed span directly (for callers that timed the
        region themselves, e.g. the executor's phase timers)."""
        if parent is not None:
            args = dict(args, parent=parent)
        ev = Span(name, cat, ts, dur,
                  tid if tid is not None else threading.get_ident(),
                  depth, args)
        with self._lock:
            self._events.append(ev)
        return ev

    def events(self, cat: Optional[str] = None) -> List[Span]:
        with self._lock:
            evs = list(self._events)
        if cat is not None:
            evs = [e for e in evs if e.cat == cat]
        return evs

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self):
        with self._lock:
            return len(self._events)

    # -- aggregation (the reference DisableProfiler printed table) ---------
    def aggregate(self, cat: Optional[str] = None) -> Dict[str, list]:
        """name -> [calls, total_s, max_s, min_s] over recorded events."""
        agg: Dict[str, list] = {}
        for e in self.events(cat=cat):
            a = agg.setdefault(e.name, [0, 0.0, 0.0, float("inf")])
            a[0] += 1
            a[1] += e.dur
            a[2] = max(a[2], e.dur)
            a[3] = min(a[3], e.dur)
        return agg

    # -- chrome://tracing export -------------------------------------------
    def chrome_events(self, cat: Optional[str] = None) -> List[dict]:
        out = []
        for e in self.events(cat=cat):
            ev = {"name": e.name, "ph": "X", "pid": 0, "tid": e.tid,
                  "ts": int(e.ts * 1e6), "dur": int(e.dur * 1e6),
                  "cat": e.cat}
            if e.args or e.depth:
                ev["args"] = dict(e.args, depth=e.depth)
            out.append(ev)
        return out

    def export_chrome(self, path: str, cat: Optional[str] = None) -> str:
        """Write the ring as chrome://tracing JSON (reference
        tools/timeline.py emits the same schema from the profiler proto)."""
        with open(path, "w") as f:
            json.dump({"traceEvents": self.chrome_events(cat=cat),
                       "displayTimeUnit": "ms"}, f)
        return path


_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer
