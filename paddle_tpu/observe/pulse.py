"""fluid-pulse: the per-process HTTP observability endpoint.

Every process of the fleet (trainer, pserver, serving replica, bench)
can expose one stdlib-HTTP thread serving its live telemetry:

    GET /metrics    Prometheus text exposition (the registry's
                    to_prometheus(), strict-grammar clean)
    GET /healthz    liveness + health verdict: per-check detail +
                    active detector alerts; 200 when ok, 503 when
                    unready. The contract fluid-fleet's router polls.
    GET /readyz     readiness subset (ready-flagged checks + detectors)
    GET /status     full JSON snapshot: metrics, step phases, recompile
                    observatory, memory observatory, health, alerts —
                    the same shape tools/telemetry_dump.py prints, so
                    one tool reads dead and live processes
    GET /flight     the flight-recorder ring as JSON, live
    GET /trace      the tracer ring as a chrome-trace document — what
                    tools/observatory.py --dump-trace stitches across
                    a live fleet (fluid-horizon)

Opt-in and flag-gated:

    fluid.set_flag("observe", True)
    port = observe.start_pulse(port=0)     # 0 = ephemeral, returns bound

With the `observe` flag off, `start_pulse` is REFUSED (RuntimeError):
a health plane over a registry that is contractually empty would lie
with 200s. The server is one daemon thread (ThreadingHTTPServer, so
concurrent scrapes don't serialize), binds 127.0.0.1 by default, and
shuts down cleanly via `stop_pulse()` — which `observe.reset_all()`
calls, so tier-1 tests can never leak the thread.

A lightweight ticker re-evaluates the health engine every
`tick_s` seconds even when nobody scrapes, so alerts still land in the
flight recorder ring of a process that dies unobserved.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from .. import flags as _flags
from . import flight as _flight
from . import health as _health
from . import memory as _memory
from . import metrics as _metrics
from .flight import json_safe as _json_safe


def status_document() -> dict:
    """The /status body — also what `tools/telemetry_dump.py` prints for
    the in-process path, keeping dead- and live-process reads shape-
    identical."""
    import os
    import time

    from . import steplog as _steplog
    from . import xray as _xray

    return {
        "pid": os.getpid(),
        "process": _xray.process_name(),
        "ts": time.time(),
        "metrics": _metrics.default_registry().snapshot(),
        "steps": _steplog.get_steplog().phase_summary(),
        "recompiles": {
            "counts": _steplog.observatory().counts(),
            "events": [e.as_dict() for e in _steplog.observatory().events()],
        },
        "memory": _memory.report(),
        # evaluate, don't just read: /status is a pull-evaluation point
        # like /healthz, so both bodies agree even with the ticker off
        "alerts": [a.as_dict()
                   for a in _health.get_engine().evaluate()],
    }


class _PulseHandler(BaseHTTPRequestHandler):
    server_version = "fluid-pulse/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):   # a scrape must never spam stderr
        pass

    def _send(self, code: int, body: bytes, ctype: str):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, doc: dict):
        self._send(code, json.dumps(_json_safe(doc), default=str).encode(),
                   "application/json")

    def do_GET(self):
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                body = _metrics.default_registry().to_prometheus().encode()
                self._send(200, body,
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path in ("/healthz", "/readyz"):
                doc = _health.get_engine().verdict(
                    ready_only=(path == "/readyz"))
                self._send_json(200 if doc["status"] == "ok" else 503, doc)
            elif path == "/status":
                self._send_json(200, status_document())
            elif path == "/flight":
                self._send_json(
                    200, _flight.get_flight().snapshot(reason="live"))
            elif path == "/trace":
                from . import tracer as _tracer
                self._send_json(200, {
                    "traceEvents": _tracer.get_tracer().chrome_events(),
                    "displayTimeUnit": "ms"})
            elif path == "/":
                self._send_json(200, {
                    "service": "fluid-pulse",
                    "endpoints": ["/metrics", "/healthz", "/readyz",
                                  "/status", "/flight", "/trace"]})
            else:
                self._send_json(404, {"error": f"no route {path!r}"})
        except Exception as e:   # a broken section must not kill the plane
            try:
                self._send_json(500,
                                {"error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass


class PulseServer:
    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 tick_s: float = 1.0):
        self._httpd = ThreadingHTTPServer((host, port), _PulseHandler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self._tick_s = float(tick_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"pulse@{self.port}")
        self._ticker = threading.Thread(
            target=self._tick_loop, daemon=True,
            name=f"pulse-tick@{self.port}")

    def start(self) -> "PulseServer":
        self._thread.start()
        if self._tick_s > 0:
            self._ticker.start()
        return self

    def _tick_loop(self):
        engine = _health.get_engine()
        while not self._stop.wait(self._tick_s):
            try:
                engine.evaluate()
            except Exception:
                pass

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass
        self._thread.join(timeout=timeout)
        if self._ticker.is_alive():
            self._ticker.join(timeout=timeout)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"


_lock = threading.Lock()
_pulse: Optional[PulseServer] = None


def start_pulse(port: int = 0, host: str = "127.0.0.1",
                tick_s: float = 1.0) -> int:
    """Start this process's pulse endpoint (idempotent — a second call
    returns the already-bound port) and arm the default health
    detectors. Returns the bound port. REFUSED while the `observe` flag
    is off."""
    global _pulse
    if not _flags.get_flag("observe"):
        raise RuntimeError(
            "observe.start_pulse() requires the observe flag: call "
            "fluid.set_flag('observe', True) (or set PADDLE_TPU_OBSERVE=1) "
            "first — a health plane over a disabled registry would "
            "report healthy no matter what")
    with _lock:
        if _pulse is not None:
            return _pulse.port
        _health.get_engine().install_default_detectors()
        _pulse = PulseServer(port=port, host=host, tick_s=tick_s).start()
        return _pulse.port


def stop_pulse(timeout: float = 5.0):
    """Shut the endpoint down (idempotent). observe.reset_all() calls
    this, so a test that started a pulse cannot leak its thread."""
    global _pulse
    with _lock:
        p, _pulse = _pulse, None
    if p is not None:
        p.stop(timeout=timeout)


def get_pulse() -> Optional[PulseServer]:
    return _pulse
