"""fluid-xray: cross-process distributed trace context.

The round-8 tracer records spans, but every span lives in ONE process's
ring: a pserver RPC shows up as a client-side wait in the trainer and an
unrelated handler blip on the server, with nothing tying them together.
This module adds the W3C Trace Context trio — a 128-bit ``trace_id``
shared by every span of one logical operation, a 64-bit ``span_id`` per
span, and the parent's span id — carried across the pserver RPC frame
and the serving request path, so a trainer+pserver chaos drill renders
as one timeline instead of N disconnected ones.

Wire format follows the W3C ``traceparent`` header
(``00-<trace_id:32hex>-<span_id:16hex>-01``); `to_wire`/`from_wire`
wrap it in a plain dict so the pickle-framed pserver RPC and any future
HTTP front-end serialize it the same way. A malformed or missing header
degrades to "no remote parent" — never an error (legacy peers without
the field keep interoperating).

Context flows through a `contextvars.ContextVar`: `span()` nests
naturally within a thread, and thread-crossing layers (MicroBatcher
futures, RPC handler threads) propagate explicitly via
`current()`/`activate()`. Emission is the caller's business to gate on
the `observe` flag — this module only allocates ids and appends to the
(bounded) tracer ring.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from contextvars import ContextVar
from typing import Optional

from .. import flags as _flags
from . import tracer as _tracer

_WIRE_KEY = "traceparent"
_BAGGAGE_KEY = "baggage"
_BAGGAGE_MAX = 16
_cv: ContextVar[Optional["SpanContext"]] = ContextVar("xray_ctx",
                                                      default=None)
# guarded_by: _id_lock — lazy id resolution in SpanContext properties.
# One process-wide lock (not per-context) keeps the context itself a
# bare 5-slot object; resolution happens once per id, off the hot path.
_id_lock = threading.Lock()


class SpanContext:
    """Identity of one span: (trace_id, span_id, parent_span_id), plus
    optional `baggage` — small string key/values that ride the WHOLE
    trace (every child inherits them, `to_wire` carries them across
    processes), e.g. ``request_kind=infer`` or a drill scenario name.

    Ids are LAZY: allocating a context on the serve hot path stores no
    ids at all (a child stores only a reference to its parent), and the
    hex id strings materialize on first property read — at trace export
    or wire encode, off the request's critical path. Resolution runs
    under a module lock so two readers racing on an unresolved id agree
    on ONE value (an id minted twice would orphan every child under the
    losing copy); resolved ids overwrite the slot, so the lock and the
    format cost are paid at most once per id. Contexts parsed off the
    wire carry their hex strings from birth and never touch the lock."""

    __slots__ = ("_tid", "_sid", "_pid", "_parent", "baggage")

    def __init__(self, trace_id=None, span_id=None, parent_id=None,
                 baggage: Optional[dict] = None,
                 parent: Optional["SpanContext"] = None):
        self._tid = trace_id
        self._sid = span_id
        self._pid = parent_id
        self._parent = parent
        self.baggage = baggage or None

    @property
    def trace_id(self) -> str:
        v = self._tid
        if v.__class__ is str:
            return v
        if v is None and self._parent is not None:
            # Inherit OUTSIDE the lock — the parent's own resolution is
            # locked and idempotent, so racing copiers all read the same
            # string, and _id_lock is not reentrant (taking it here
            # would deadlock the chain walk).
            v = self._tid = self._parent.trace_id
            return v
        with _id_lock:
            v = self._tid
            if v.__class__ is str:
                return v
            v = (format(_get_rng().getrandbits(128), "032x")
                 if v is None else format(v, "032x"))
            self._tid = v
        return v

    @property
    def span_id(self) -> str:
        v = self._sid
        if v.__class__ is str:
            return v
        with _id_lock:
            v = self._sid
            if v.__class__ is str:
                return v
            v = (format(_get_rng().getrandbits(64), "016x")
                 if v is None else format(v, "016x"))
            self._sid = v
        return v

    @property
    def parent_id(self) -> Optional[str]:
        v = self._pid
        if v is None:
            p = self._parent
            if p is None:
                return None
            v = self._pid = p.span_id
            return v
        if v.__class__ is not str:
            v = self._pid = format(v, "016x")
        return v

    def child(self) -> "SpanContext":
        """New span in the SAME trace, parented here (baggage rides)."""
        return SpanContext(baggage=self.baggage, parent=self)

    def with_baggage(self, **kv) -> "SpanContext":
        """Same span identity, baggage extended with `kv` (values are
        stringified — baggage is a wire-portable str->str map). Resolves
        lazy ids first: the copy must share the ORIGINAL's identity, not
        mint its own on a later read."""
        bag = dict(self.baggage or {})
        bag.update({str(k): str(v) for k, v in kv.items()})
        return SpanContext(self.trace_id, self.span_id, self.parent_id,
                           baggage=bag)

    def trace_args(self) -> dict:
        """The span-identity fields every xray tracer event carries."""
        args = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            args["parent_span_id"] = self.parent_id
        return args

    def __repr__(self):
        return (f"SpanContext(trace={self.trace_id}, span={self.span_id}, "
                f"parent={self.parent_id})")

    def __eq__(self, other):
        return (isinstance(other, SpanContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id
                and self.parent_id == other.parent_id
                and (self.baggage or {}) == (other.baggage or {}))


# ids need uniqueness, not unpredictability: a PRNG seeded once from the
# OS beats an os.urandom syscall per id on the serve hot path (every
# request allocates 2+ span ids; the horizon bench prices this). Seeded
# lazily PER PROCESS KEYED ON PID so a fork between imports can't make
# two processes' id streams collide.
_rng_pid: Optional[int] = None
_rng: Optional[random.Random] = None


def _get_rng() -> random.Random:
    global _rng, _rng_pid
    if _rng is None or _rng_pid != os.getpid():
        _rng = random.Random(int.from_bytes(os.urandom(16), "big"))
        _rng_pid = os.getpid()
    return _rng


def new_trace_id() -> str:
    return f"{_get_rng().getrandbits(128):032x}"


def new_span_id() -> str:
    return f"{_get_rng().getrandbits(64):016x}"


# the trace-flag read sits on the per-request serve hot path (2+
# child_of calls per request), so it is memoized on the flag registry's
# version: one int compare per call instead of registry dict lookups,
# and a set_flag("trace", ...) flip still takes effect immediately
# (every set_flag bumps the version)
_trace_cache = (-1, True)


def _trace_on() -> bool:
    global _trace_cache
    ver = _flags.version()
    cached = _trace_cache
    if cached[0] != ver:
        cached = _trace_cache = (ver, bool(_flags.get_flag("trace")))
    return cached[1]


def current() -> Optional[SpanContext]:
    """The active span context of this thread/task, or None."""
    return _cv.get()


def child_of(parent: Optional[SpanContext] = None,
             inherit: bool = True) -> Optional[SpanContext]:
    """A fresh span context: child of `parent` (or of the ambient context
    when `inherit`), else the root of a brand-new trace. Returns None
    while the `trace` flag is off — every call site null-guards, so the
    kill switch degrades the whole plane to legacy frames + no spans."""
    if not _trace_on():
        return None
    if parent is None and inherit:
        parent = current()
    if parent is not None:
        return parent.child()
    return SpanContext()


@contextlib.contextmanager
def activate(ctx: Optional[SpanContext]):
    """Make `ctx` the ambient context for the body (server handlers
    adopting a remote parent; executor threads adopting a request's)."""
    token = _cv.set(ctx)
    try:
        yield ctx
    finally:
        _cv.reset(token)


def set_current(ctx: Optional[SpanContext]):
    """Non-context-manager activation: returns a token for
    `unset_current`. The serve executor's batch loop uses this pair
    instead of `activate` — a generator context manager costs a few
    microseconds per batch, which the horizon A/B prices. Prefer
    `activate` anywhere that doesn't run per-request."""
    return _cv.set(ctx)


def unset_current(token):
    _cv.reset(token)


@contextlib.contextmanager
def span(name: str, cat: str = "xray", parent: Optional[SpanContext] = None,
         **args):
    """Timed span recorded into the tracer ring WITH trace identity.

    Like `Tracer.span` but each event carries trace_id/span_id/
    parent_span_id, and the new context is ambient for the body so
    nested spans (and outbound RPCs) join the trace. The event is
    recorded even when the body raises, tagged ``error=<type>``.

    With the `trace` flag off the body runs with no ids allocated, no
    ambient context, and nothing recorded — the yielded value is None."""
    ctx = child_of(parent)
    if ctx is None:
        yield None
        return
    ts = time.time()
    t0 = time.perf_counter()
    err = None
    token = _cv.set(ctx)
    try:
        yield ctx
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        _cv.reset(token)
        if err is not None:
            args = dict(args, error=err)
        _tracer.get_tracer().record_ctx(name, ts, time.perf_counter() - t0,
                                        cat, ctx, args)


def record_span(name: str, ctx: Optional[SpanContext], ts: float,
                dur: float, cat: str = "xray", **args):
    """Append an already-timed span under an explicit context (callers
    that measured the region themselves, e.g. per-attempt RPC timing).
    A None ctx (trace flag off) is a no-op."""
    if ctx is None:
        return
    return _tracer.get_tracer().record_ctx(name, ts, dur, cat, ctx, args)


def tracer():
    """The process tracer (hot-path callers that record straight via
    `Tracer.record_ctx` without the record_span null-check hop)."""
    return _tracer.get_tracer()


# -- wire format ------------------------------------------------------------

def to_traceparent(ctx: SpanContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(value) -> Optional[SpanContext]:
    """Parse a ``traceparent`` string; any malformation returns None (a
    legacy or buggy peer must degrade to "no parent", never to an
    error)."""
    if not isinstance(value, str):
        return None
    parts = value.split("-")
    if len(parts) != 4:
        return None
    _ver, trace_id, span_id, _flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return SpanContext(trace_id, span_id)


def to_wire(ctx: SpanContext) -> dict:
    meta = {_WIRE_KEY: to_traceparent(ctx)}
    if ctx.baggage:
        meta[_BAGGAGE_KEY] = dict(ctx.baggage)
    return meta


def from_wire(meta) -> Optional[SpanContext]:
    """Extract a remote parent context from an RPC frame's meta dict.
    Missing/malformed -> None (legacy peer interop). Baggage survives
    the hop when present and well-formed (a str->str dict, bounded to
    `_BAGGAGE_MAX` entries — a hostile/buggy peer cannot bloat every
    downstream frame)."""
    if not isinstance(meta, dict):
        return None
    ctx = parse_traceparent(meta.get(_WIRE_KEY))
    if ctx is None:
        return None
    bag = meta.get(_BAGGAGE_KEY)
    if isinstance(bag, dict) and bag:
        clean = {str(k): str(v) for k, v in list(bag.items())[:_BAGGAGE_MAX]}
        ctx = SpanContext(ctx.trace_id, ctx.span_id, ctx.parent_id,
                          baggage=clean)
    return ctx


def baggage(key: Optional[str] = None):
    """The ambient context's baggage dict (or one value by `key`);
    empty/None when there is no ambient trace."""
    ctx = current()
    bag = (ctx.baggage or {}) if ctx is not None else {}
    return bag.get(key) if key is not None else bag


# -- process naming (chrome-trace merge) ------------------------------------

def set_process_name(name: str):
    """Name this process in chrome-trace exports (`tools/telemetry_dump.py
    --merge` stitches per-process files; the name is what perfetto shows
    per track)."""
    _tracer.set_process_name(name)


def process_name() -> str:
    return _tracer.get_process_name()


def reset():
    """Drop the ambient context of THIS thread (tests)."""
    _cv.set(None)
