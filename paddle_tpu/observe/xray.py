"""fluid-xray: cross-process distributed trace context.

The round-8 tracer records spans, but every span lives in ONE process's
ring: a pserver RPC shows up as a client-side wait in the trainer and an
unrelated handler blip on the server, with nothing tying them together.
This module adds the W3C Trace Context trio — a 128-bit ``trace_id``
shared by every span of one logical operation, a 64-bit ``span_id`` per
span, and the parent's span id — carried across the pserver RPC frame
and the serving request path, so a trainer+pserver chaos drill renders
as one timeline instead of N disconnected ones.

Wire format follows the W3C ``traceparent`` header
(``00-<trace_id:32hex>-<span_id:16hex>-01``); `to_wire`/`from_wire`
wrap it in a plain dict so the pickle-framed pserver RPC and any future
HTTP front-end serialize it the same way. A malformed or missing header
degrades to "no remote parent" — never an error (legacy peers without
the field keep interoperating).

Context flows through a `contextvars.ContextVar`: `span()` nests
naturally within a thread, and thread-crossing layers (MicroBatcher
futures, RPC handler threads) propagate explicitly via
`current()`/`activate()`. Emission is the caller's business to gate on
the `observe` flag — this module only allocates ids and appends to the
(bounded) tracer ring.
"""

from __future__ import annotations

import contextlib
import os
import time
from contextvars import ContextVar
from typing import Optional

from . import tracer as _tracer

_WIRE_KEY = "traceparent"
_cv: ContextVar[Optional["SpanContext"]] = ContextVar("xray_ctx",
                                                      default=None)


class SpanContext:
    """Identity of one span: (trace_id, span_id, parent_span_id)."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    def child(self) -> "SpanContext":
        """New span in the SAME trace, parented here."""
        return SpanContext(self.trace_id, new_span_id(), self.span_id)

    def trace_args(self) -> dict:
        """The span-identity fields every xray tracer event carries."""
        args = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            args["parent_span_id"] = self.parent_id
        return args

    def __repr__(self):
        return (f"SpanContext(trace={self.trace_id}, span={self.span_id}, "
                f"parent={self.parent_id})")

    def __eq__(self, other):
        return (isinstance(other, SpanContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id
                and self.parent_id == other.parent_id)


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def current() -> Optional[SpanContext]:
    """The active span context of this thread/task, or None."""
    return _cv.get()


def child_of(parent: Optional[SpanContext] = None,
             inherit: bool = True) -> SpanContext:
    """A fresh span context: child of `parent` (or of the ambient context
    when `inherit`), else the root of a brand-new trace."""
    if parent is None and inherit:
        parent = current()
    if parent is not None:
        return parent.child()
    return SpanContext(new_trace_id(), new_span_id(), None)


@contextlib.contextmanager
def activate(ctx: Optional[SpanContext]):
    """Make `ctx` the ambient context for the body (server handlers
    adopting a remote parent; executor threads adopting a request's)."""
    token = _cv.set(ctx)
    try:
        yield ctx
    finally:
        _cv.reset(token)


@contextlib.contextmanager
def span(name: str, cat: str = "xray", parent: Optional[SpanContext] = None,
         **args):
    """Timed span recorded into the tracer ring WITH trace identity.

    Like `Tracer.span` but each event carries trace_id/span_id/
    parent_span_id, and the new context is ambient for the body so
    nested spans (and outbound RPCs) join the trace. The event is
    recorded even when the body raises, tagged ``error=<type>``."""
    ctx = child_of(parent)
    ts = time.time()
    t0 = time.perf_counter()
    err = None
    token = _cv.set(ctx)
    try:
        yield ctx
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        _cv.reset(token)
        a = dict(args, **ctx.trace_args())
        if err is not None:
            a["error"] = err
        _tracer.get_tracer().record(name, ts, time.perf_counter() - t0,
                                    cat=cat, **a)


def record_span(name: str, ctx: SpanContext, ts: float, dur: float,
                cat: str = "xray", **args):
    """Append an already-timed span under an explicit context (callers
    that measured the region themselves, e.g. per-attempt RPC timing)."""
    _tracer.get_tracer().record(name, ts, dur, cat=cat,
                                **dict(args, **ctx.trace_args()))


# -- wire format ------------------------------------------------------------

def to_traceparent(ctx: SpanContext) -> str:
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(value) -> Optional[SpanContext]:
    """Parse a ``traceparent`` string; any malformation returns None (a
    legacy or buggy peer must degrade to "no parent", never to an
    error)."""
    if not isinstance(value, str):
        return None
    parts = value.split("-")
    if len(parts) != 4:
        return None
    _ver, trace_id, span_id, _flags = parts
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    return SpanContext(trace_id, span_id)


def to_wire(ctx: SpanContext) -> dict:
    return {_WIRE_KEY: to_traceparent(ctx)}


def from_wire(meta) -> Optional[SpanContext]:
    """Extract a remote parent context from an RPC frame's meta dict.
    Missing/malformed -> None (legacy peer interop)."""
    if not isinstance(meta, dict):
        return None
    return parse_traceparent(meta.get(_WIRE_KEY))


# -- process naming (chrome-trace merge) ------------------------------------

def set_process_name(name: str):
    """Name this process in chrome-trace exports (`tools/telemetry_dump.py
    --merge` stitches per-process files; the name is what perfetto shows
    per track)."""
    _tracer.set_process_name(name)


def process_name() -> str:
    return _tracer.get_process_name()


def reset():
    """Drop the ambient context of THIS thread (tests)."""
    _cv.set(None)
