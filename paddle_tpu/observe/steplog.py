"""Per-step phase accounting + the recompilation observatory.

Two runtime questions dominate TPU cost and were previously invisible:

1. *Where does a step's host time go?* `StepStats` records the wall time
   of each host phase around the jitted call — feed conversion, state
   gather, device dispatch+compute, state write-back, fetch transfer —
   for every `Executor`/`ParallelExecutor` run when the `observe` flag is
   on. bench.py records the aggregate next to each headline number.

2. *Why did XLA recompile?* The static lint (analysis/, PR 2) can only
   WARN about feed-shape recompile hazards; the observatory closes the
   loop by recording every actual jit cache miss with its attributed
   cause:

   - ``first_call``       first compile of this program (expected)
   - ``feed_shape``       same feed names, new shapes/dtypes — the
                          hazard the lint warns about, now caught live
   - ``program_version``  the program was mutated after compilation
   - ``copts_change``     xla_compiler_options changed between runs
   - ``feed_names``       a different set of feed variables was bound
   - ``fetch_set``        a different fetch list forced a new executable
   - ``new_scope``        the same program bound against a different
                          Scope (train/test scopes, per-request scopes)
   - ``options_change``   an executor-setting flip re-keyed the compile
                          cache (amp / check_nan_inf / dropout_impl /
                          random_seed)
   - ``uncached``         use_program_cache=False (tests probing
                          recompilation; never attributed further)
   - ``warmup``           an ahead-of-time compile the serving layer
                          (serve/) deliberately provoked while warming a
                          bucket ladder — expected, like ``first_call``
   - ``padding_bucket``   a shape miss on a ``serving``-source handle:
                          the request's padded shape was NOT in the
                          warmed bucket ladder. Same mechanism as
                          ``feed_shape`` but attributed separately so
                          `--assert-no-recompiles` distinguishes a
                          mis-sized ladder from a genuine cache bug

   Compile events are recorded regardless of the `observe` flag — a
   compile costs seconds, the record costs microseconds, and the
   observatory is the whole point of `tools/telemetry_dump.py
   --assert-no-recompiles`. Only the per-step shape *tracking* that
   detects `feed_shape` misses is flag-gated (it is on the hot path).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from . import flight as _flight
from . import metrics as _metrics
from . import tracer as _tracer

PHASES = ("feed_convert", "state_gather", "device_compute", "write_back",
          "fetch", "bind")


class StepStats:
    """Host-side phase wall times (seconds) of one run()."""

    __slots__ = ("program_uid", "source", "ts", "phases", "total")

    def __init__(self, program_uid: int, source: str, ts: float,
                 phases: Dict[str, float]):
        self.program_uid = program_uid
        self.source = source          # "executor" | "parallel"
        self.ts = ts
        self.phases = phases
        self.total = sum(phases.values())

    def as_dict(self) -> dict:
        return {"program_uid": self.program_uid, "source": self.source,
                "ts": self.ts, "total_us": round(self.total * 1e6, 2),
                "phases_us": {k: round(v * 1e6, 2)
                              for k, v in self.phases.items()}}


class StepLog:
    """Bounded record of recent StepStats + running per-phase totals."""

    def __init__(self, capacity: int = 1024):
        self._steps: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._totals = {p: 0.0 for p in PHASES}
        self._count = 0
        # (registry generation, counter, histogram): resolved once per
        # registry generation instead of two get-or-create registry-lock
        # round trips on every observed step
        self._mcache = None

    def _metric_handles(self):
        reg = _metrics.default_registry()
        gen = reg.generation()
        mc = self._mcache
        if mc is None or mc[0] != gen:
            mc = self._mcache = (
                gen,
                reg.counter("executor_steps_total",
                            "run() calls instrumented by the steplog"),
                reg.histogram("executor_step_phase_us",
                              "host wall time per step phase "
                              "(microseconds)"))
        return mc[1], mc[2]

    def record(self, stats: StepStats, emit_metrics: bool = True,
               emit_trace: bool = True):
        with self._lock:
            self._steps.append(stats)
            for p, v in stats.phases.items():
                self._totals[p] = self._totals.get(p, 0.0) + v
            self._count += 1
        if emit_metrics:
            c, h = self._metric_handles()
            c.inc(source=stats.source)
            for p, v in stats.phases.items():
                h.observe(v * 1e6, phase=p, source=stats.source)
        if emit_trace:
            _tracer.get_tracer().record(
                "step", stats.ts, stats.total, cat="step",
                **{f"{k}_us": round(v * 1e6, 2)
                   for k, v in stats.phases.items()})
        # flight recorder: callers only invoke record() when observing,
        # so this rides the same gate as the metric writes
        _flight.note("step", program_uid=stats.program_uid,
                     source=stats.source,
                     total_us=round(stats.total * 1e6, 2))

    def recent(self, n: int = 16) -> List[StepStats]:
        with self._lock:
            return list(self._steps)[-n:]

    def phase_summary(self, reset: bool = False) -> dict:
        """Aggregated per-phase totals (µs) since the last reset."""
        with self._lock:
            out = {"steps": self._count,
                   "phase_us": {p: round(v * 1e6, 2)
                                for p, v in self._totals.items() if v},
                   "mean_step_us": round(
                       sum(self._totals.values()) * 1e6
                       / max(self._count, 1), 2)}
            if reset:
                self._totals = {p: 0.0 for p in PHASES}
                self._count = 0
        return out

    def clear(self):
        with self._lock:
            self._steps.clear()
            self._totals = {p: 0.0 for p in PHASES}
            self._count = 0


class RecompileEvent:
    __slots__ = ("ts", "program_uid", "cause", "source", "detail")

    def __init__(self, ts, program_uid, cause, source, detail):
        self.ts = ts
        self.program_uid = program_uid
        self.cause = cause
        self.source = source
        self.detail = detail

    def as_dict(self) -> dict:
        return {"ts": self.ts, "program_uid": self.program_uid,
                "cause": self.cause, "source": self.source,
                "detail": self.detail}

    def __repr__(self):
        return (f"RecompileEvent(uid={self.program_uid}, "
                f"cause={self.cause!r}, source={self.source!r})")


# causes that are expected on a healthy steady-state run and therefore
# ignored by --assert-no-recompiles (the first compile of each program
# has to happen, and a serving warmup compiles its bucket ladder ahead
# of traffic on purpose; everything else is a recompile someone should
# explain)
EXPECTED_CAUSES = ("first_call", "warmup")


class RecompilationObservatory:
    """Records every executor-level compile with an attributed cause.

    Attribution compares the miss against what this process has already
    compiled for the same program uid, in priority order: new version →
    ``program_version``; new compiler options → ``copts_change``; new
    feed-name set → ``feed_names``; new fetch list → ``fetch_set``; new
    scope → ``new_scope``; anything else that re-keyed the compile cache
    (amp / check_nan_inf / dropout_impl / random_seed flips) →
    ``options_change``. Run-time shape tracking (flag-gated, see note in
    the module docstring) reports jax-level retraces of an already-bound
    entry as ``feed_shape``."""

    def __init__(self, capacity: int = 256):
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        # program uid -> {"versions", "copts", "feed_sigs", "fetch_sigs",
        #                 "scopes"}
        self._seen: Dict[int, dict] = {}

    def note_entry_build(self, program_uid: int, version: int,
                         feed_sig: Tuple, fetch_sig: Tuple, copts_sig,
                         source: str = "executor",
                         scope_uid=None) -> str:
        """Called on every executor compile-cache miss (a new
        _CompiledProgram is about to be built). Returns the cause."""
        with self._lock:
            s = self._seen.get(program_uid)
            if s is None:
                cause = "first_call"
                s = self._seen[program_uid] = {
                    "versions": set(), "copts": set(),
                    "feed_sigs": set(), "fetch_sigs": set(),
                    "scopes": set()}
            elif version not in s["versions"]:
                cause = "program_version"
            elif copts_sig not in s["copts"]:
                cause = "copts_change"
            elif feed_sig not in s["feed_sigs"]:
                cause = "feed_names"
            elif fetch_sig not in s["fetch_sigs"]:
                cause = "fetch_set"
            elif scope_uid is not None and scope_uid not in s["scopes"]:
                cause = "new_scope"
            else:
                # every observed key dimension matched, so the re-key came
                # from an executor-setting flip (amp / check_nan_inf /
                # dropout_impl / random_seed)
                cause = "options_change"
            s["versions"].add(version)
            s["copts"].add(copts_sig)
            s["feed_sigs"].add(feed_sig)
            s["fetch_sigs"].add(fetch_sig)
            if scope_uid is not None:
                s["scopes"].add(scope_uid)
            self._events.append(RecompileEvent(
                time.time(), program_uid, cause, source,
                {"version": version, "feeds": list(feed_sig),
                 "fetches": list(fetch_sig)}))
        self._emit_metric(cause, source)
        return cause

    def note_shape_miss(self, program_uid: int, shape_sig, source: str,
                        cause: str = "feed_shape"):
        """A bound entry saw a NEW feed shape/dtype signature: jax.jit
        will retrace and XLA will recompile. This is the live counterpart
        of the lint's feed-shape recompile hazard. On a ``serving``-source
        handle the caller attributes it ``padding_bucket`` instead — the
        bucket planner should have padded the request onto a warmed rung,
        so a miss means the ladder is mis-sized, not that the jit cache
        misbehaved."""
        with self._lock:
            self._events.append(RecompileEvent(
                time.time(), program_uid, cause, source,
                {"shapes": {n: list(shp)
                            for n, shp, _ in shape_sig}}))
        self._emit_metric(cause, source)

    def record(self, program_uid: int, cause: str, source: str,
               detail=None):
        """Direct record without attribution (e.g. `uncached` runs)."""
        with self._lock:
            self._events.append(RecompileEvent(
                time.time(), program_uid, cause, source, detail))
        self._emit_metric(cause, source)

    @staticmethod
    def _emit_metric(cause: str, source: str):
        _metrics.counter(
            "executor_recompiles_total",
            "executor compile events by attributed cause").inc(
                cause=cause, source=source)
        # compile events are never hot — they go to the black box
        # unconditionally, like the metric above
        _flight.note("compile", cause=cause, source=source)

    def events(self) -> List[RecompileEvent]:
        with self._lock:
            return list(self._events)

    def counts(self) -> Dict[str, int]:
        """Per-cause counts over the BOUNDED event ring — right for short
        runs and detail inspection. For cumulative whole-run counts read
        the `executor_recompiles_total` metrics counter instead (events
        older than the ring capacity fall out of this tally)."""
        out: Dict[str, int] = {}
        for e in self.events():
            out[e.cause] = out.get(e.cause, 0) + 1
        return out

    def unexpected(self) -> List[RecompileEvent]:
        """Events whose cause is not in EXPECTED_CAUSES — the set
        --assert-no-recompiles fails on."""
        return [e for e in self.events() if e.cause not in EXPECTED_CAUSES]

    def clear(self):
        with self._lock:
            self._events.clear()
            self._seen.clear()


_steplog = StepLog()
_observatory = RecompilationObservatory()


def get_steplog() -> StepLog:
    return _steplog


def observatory() -> RecompilationObservatory:
    return _observatory


def shape_sig(feed_arrays: Dict) -> Tuple:
    """Canonical (name, shape, dtype) signature of a feed dict — the part
    of the jax.jit cache key the executor can observe cheaply."""
    return tuple(sorted(
        (n, tuple(getattr(v, "shape", ())), str(getattr(v, "dtype", "")))
        for n, v in feed_arrays.items()))


def track_shapes(entry, program_uid: int, feed_arrays: Dict,
                 source: str = "executor"):
    """Flag-gated per-step shape tracking: detect jax-level retraces of a
    bound entry. The first signature an entry ever runs is covered by its
    build event; every NEW signature after that is a `feed_shape` miss —
    or, on a serving handle (where the bucket planner guarantees every
    steady-state shape was warmed ahead of time), a `padding_bucket`
    miss."""
    sig = shape_sig(feed_arrays)
    seen = getattr(entry, "_shape_sigs", None)
    if seen is None:
        seen = entry._shape_sigs = set()
    if sig not in seen:
        if seen:
            cause = "padding_bucket" if source == "serving" else "feed_shape"
            observatory().note_shape_miss(program_uid, sig, source, cause)
        seen.add(sig)


def preseed_shapes(entry, feed_arrays: Dict):
    """Register a feed signature as already-seen on a bound entry WITHOUT
    recording a shape-miss event. The serving warmup uses this: it runs
    each bucket shape once ahead of traffic (recording those compiles as
    the expected `warmup` cause via the observatory), and pre-seeding
    keeps the tracker from re-flagging the warmed shapes as misses —
    including when warmup ran with the `observe` flag off and the flag is
    flipped on later."""
    sig = shape_sig(feed_arrays)
    seen = getattr(entry, "_shape_sigs", None)
    if seen is None:
        seen = entry._shape_sigs = set()
    seen.add(sig)
