"""Quantization layers (reference: fake_quantize_op.cc wrappers used by the
quantization-aware-training passes; contrib/float16 utilities)."""

from __future__ import annotations

from ..layer_helper import LayerHelper


def fake_quantize(x, bit_length=8, quantize_type="abs_max", name=None,
                  in_scale=None, is_test=False):
    """Quantize-dequantize in float with a straight-through gradient
    (reference fake_quantize_op.cc). Returns (out, scale)."""
    helper = LayerHelper("fake_quantize", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    scale = helper.create_variable_for_type_inference(dtype="float32")
    scale.stop_gradient = True
    if quantize_type == "abs_max":
        helper.append_op("fake_quantize_abs_max",
                         inputs={"X": [x.name]},
                         outputs={"Out": [out.name],
                                  "OutScale": [scale.name]},
                         attrs={"bit_length": bit_length})
    elif quantize_type == "range_abs_max":
        inputs = {"X": [x.name]}
        if in_scale is not None:
            # the running scale is REAL state: write OutScale back onto the
            # in_scale var so the range accumulates across steps (reference
            # updates the persistable InScale buffer in place)
            inputs["InScale"] = [in_scale.name]
            scale = in_scale
        helper.append_op("fake_quantize_range_abs_max",
                         inputs=inputs,
                         outputs={"Out": [out.name],
                                  "OutScale": [scale.name]},
                         attrs={"bit_length": bit_length,
                                "is_test": is_test})
    else:
        raise ValueError(f"unknown quantize_type {quantize_type!r}")
    return out, scale


def fake_dequantize(x, scale, max_range=127.0, name=None):
    helper = LayerHelper("fake_dequantize", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("fake_dequantize_max_abs",
                     inputs={"X": [x.name], "Scale": [scale.name]},
                     outputs={"Out": [out.name]},
                     attrs={"max_range": float(max_range)})
    return out
