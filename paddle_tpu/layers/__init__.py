"""Layers DSL (reference: python/paddle/fluid/layers/)."""

from .io import (data, py_reader, open_recordio_file,  # noqa: F401
                 double_buffer, ListenAndServ, Send, Recv,
                 read_file, shuffle, batch, open_files,
                 random_data_generator, load, Preprocessor)
from .nn import *  # noqa: F401,F403
from .tensor import (create_tensor, create_global_var, fill_constant,  # noqa: F401
                     fill_constant_batch_size_like, assign, cast, concat, sums,
                     argmax, argmin, argsort, zeros, ones, reverse,
                     create_parameter)
from .ops import *  # noqa: F401,F403
from .metric_op import accuracy, auc  # noqa: F401
from .loss_layers import (nce, hsigmoid, linear_chain_crf,  # noqa: F401
                          crf_decoding, warpctc, edit_distance)
from .control_flow import (While, StaticRNN, Switch, DynamicRNN,  # noqa: F401
                           IfElse, increment, less_than, equal,
                           create_array, array_write, array_read,
                           array_length, lod_rank_table, max_sequence_len,
                           lod_tensor_to_array, array_to_lod_tensor,
                           shrink_memory, reorder_lod_tensor_by_rank,
                           Print, is_empty, ParallelDo)
from . import learning_rate_scheduler  # noqa: F401
from .learning_rate_scheduler import (exponential_decay,  # noqa: F401
                                      natural_exp_decay, inverse_time_decay,
                                      polynomial_decay, piecewise_decay,
                                      noam_decay, append_LARS)
from . import detection  # noqa: F401
from .detection import (prior_box, anchor_generator, iou_similarity,  # noqa: F401
                        box_coder, bipartite_match, target_assign,
                        multiclass_nms, detection_output, multi_box_head,
                        detection_map, ssd_loss, rpn_target_assign,
                        mine_hard_examples, polygon_box_transform)
from .quant import fake_quantize, fake_dequantize  # noqa: F401
from .math_op_patch import monkey_patch_variable

monkey_patch_variable()
