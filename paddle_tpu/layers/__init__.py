"""Layers DSL (reference: python/paddle/fluid/layers/)."""

from .io import (data, py_reader, open_recordio_file,  # noqa: F401
                 double_buffer, ListenAndServ, Send, Recv)
from .nn import *  # noqa: F401,F403
from .tensor import (create_tensor, create_global_var, fill_constant,  # noqa: F401
                     fill_constant_batch_size_like, assign, cast, concat, sums,
                     argmax, argmin, zeros, ones, reverse)
from .ops import *  # noqa: F401,F403
from .metric_op import accuracy, auc  # noqa: F401
from .loss_layers import (nce, hsigmoid, linear_chain_crf,  # noqa: F401
                          crf_decoding, warpctc, edit_distance)
from .control_flow import (While, StaticRNN, Switch, DynamicRNN,  # noqa: F401
                           IfElse, increment, less_than, equal,
                           create_array, array_write, array_read,
                           array_length, lod_rank_table, max_sequence_len,
                           lod_tensor_to_array, array_to_lod_tensor,
                           shrink_memory, reorder_lod_tensor_by_rank)
from . import learning_rate_scheduler  # noqa: F401
from . import detection  # noqa: F401
from .quant import fake_quantize, fake_dequantize  # noqa: F401
from .math_op_patch import monkey_patch_variable

monkey_patch_variable()
