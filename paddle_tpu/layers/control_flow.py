"""Control-flow DSL: While / StaticRNN / Switch / increment / array ops.

Capability parity with reference python/paddle/fluid/layers/control_flow.py
(While :654, StaticRNN :429, Switch :1282, IfElse :1408, increment,
less_than, array_write/array_read). Sub-blocks become nested IR blocks and
lower to lax.while_loop / lax.scan / lax.cond (ops/control.py) — the
reference's nested-Executor StepScopes machinery has no TPU analog because
the loop never leaves the compiled program.
"""

from __future__ import annotations

import contextlib

from ..core import ir
from ..layer_helper import LayerHelper
from . import tensor as lt


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out_name = x.name if in_place else None
    if out_name is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        out_name = out.name
    helper.append_op("increment", inputs={"X": [x.name]},
                     outputs={"Out": [out_name]}, attrs={"step": float(value)})
    return x if in_place else out


def less_than(x, y, cond=None):
    helper = LayerHelper("less_than")
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op("less_than", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [cond.name]}, attrs={"axis": -1})
    return cond


def equal(x, y, cond=None):
    helper = LayerHelper("equal")
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op("equal", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [cond.name]}, attrs={"axis": -1})
    return cond


class While:
    """`with While(cond).block(): ...` loop (reference control_flow.py:654).

    The body must re-assign `cond` (via layers.assign / logical ops) so the
    loop terminates. All outer variables assigned inside the body become
    loop-carried state.
    """

    def __init__(self, cond, is_test=False, name=None):
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent_block = program.current_block()
        sub_block = program._create_block()
        yield
        program._rollback()

        # loop-carried state: vars written in the sub-block that exist in an
        # enclosing block (assign-out pattern), plus the condition.
        carry = []
        for op in sub_block.ops:
            for n in op.output_arg_names:
                if n in parent_block.vars or (
                        parent_block._find_var_recursive(n) is not None
                        and n not in sub_block.vars):
                    if n not in carry:
                        carry.append(n)
        if self.cond_var.name not in carry:
            carry.append(self.cond_var.name)
        x_inputs = sorted(set(ir.external_reads(program, sub_block.idx))
                          | set(carry))

        parent_block.append_op(
            "while",
            inputs={"X": [n for n in x_inputs
                          if parent_block._find_var_recursive(n) is not None],
                    "Condition": [self.cond_var.name]},
            outputs={"Out": list(carry)},
            attrs={"sub_block": sub_block.idx, "carry_vars": list(carry),
                   "cond_var": self.cond_var.name})


class StaticRNN:
    """Scan-based RNN builder (reference control_flow.py:429).

    with rnn.step():
        x_t = rnn.step_input(x)       # [B, T, D] -> [B, D]
        h = rnn.memory(init=h0)       # carried state
        nh = some_layers(x_t, h)
        rnn.update_memory(h, nh)
        rnn.step_output(nh)
    outs = rnn()                      # [B, T, H]
    """

    def __init__(self, name=None, num_steps=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.num_steps = num_steps  # for input-free (decode) loops
        self._step_inputs = []   # (outer_name, inner_name)
        self._memories = []      # (pre_name, mem_name, init_name)
        self._step_outputs = []  # inner names
        self._outputs = []       # outer Vars
        self._sub_block = None
        self._parent_block = None

    @contextlib.contextmanager
    def step(self):
        program = self.helper.main_program
        self._parent_block = program.current_block()
        self._sub_block = program._create_block()
        yield
        program._rollback()
        self._finalize()

    def step_input(self, x):
        inner = self._sub_block.create_var(
            name=f"{self.helper.name}.in_{len(self._step_inputs)}",
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype)
        self._step_inputs.append((x.name, inner.name))
        return inner

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory() needs `init` or (shape, batch_ref)")
            # build init in the PARENT block
            program = self.helper.main_program
            cur = program._current_block_idx
            program._current_block_idx = self._parent_block.idx
            try:
                from . import tensor as _t
                init = _t.fill_constant_batch_size_like(
                    batch_ref, [0] + list(shape[1:] if len(shape) > 1 else shape),
                    "float32", init_value, input_dim_idx=0, output_dim_idx=0)
            finally:
                program._current_block_idx = cur
        pre = self._sub_block.create_var(
            name=f"{self.helper.name}.mem_{len(self._memories)}",
            shape=init.shape, dtype=init.dtype)
        self._memories.append([pre.name, None, init.name])
        return pre

    def update_memory(self, mem, var):
        for m in self._memories:
            if m[0] == mem.name:
                m[1] = var.name
                return
        raise ValueError(f"{mem.name} is not a memory of this StaticRNN")

    def step_output(self, o):
        self._step_outputs.append(o.name)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _finalize(self):
        for m in self._memories:
            if m[1] is None:
                raise ValueError(f"memory {m[0]} was never update_memory()-ed")
        outs = []
        for inner_name in self._step_outputs:
            inner = self._sub_block.vars.get(inner_name)
            shape = ((inner.shape[0], -1) + tuple(inner.shape[1:])
                     if inner is not None and inner.shape else ())
            out = self._parent_block.create_var(
                name=f"{self.helper.name}.out_{len(outs)}",
                shape=shape, dtype=inner.dtype if inner else "float32")
            outs.append(out)
        self._outputs = outs
        program = self.helper.main_program
        externals = [n for n in ir.external_reads(program, self._sub_block.idx)
                     if self._parent_block._find_var_recursive(n) is not None]
        init_names = [m[2] for m in self._memories]
        x_names = [outer for outer, _ in self._step_inputs]
        all_ins = list(dict.fromkeys(x_names + init_names + externals))
        self._parent_block.append_op(
            "static_rnn",
            inputs={"X": all_ins},
            outputs={"Out": [o.name for o in outs]},
            attrs={"sub_block": self._sub_block.idx,
                   "step_inputs": [list(p) for p in self._step_inputs],
                   "memories": [list(m) for m in self._memories],
                   "step_outputs": list(self._step_outputs),
                   "num_steps": self.num_steps or 0})

    def __call__(self):
        if len(self._outputs) == 1:
            return self._outputs[0]
        return self._outputs


class Switch:
    """Reference control_flow.py:1282 — used mainly for LR warmup schedules.
    First matching case wins, as in the reference: each case's effective
    condition is `its condition AND none-of-the-previous`; the default fires
    only when every case condition was false. Each case lowers to a
    lax.cond over a sub-block.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._prev_conds = []

    @contextlib.contextmanager
    def case(self, condition):
        yield from self._record(condition)

    @contextlib.contextmanager
    def default(self):
        yield from self._record(None)

    def _record(self, condition):
        program = self.helper.main_program
        parent = program.current_block()
        sub = program._create_block()
        yield
        program._rollback()
        outs = sorted({n for op in sub.ops for n in op.output_arg_names
                       if parent._find_var_recursive(n) is not None})
        eff = self._effective_cond(parent, condition)
        if condition is not None:
            self._prev_conds.append(condition)
        externals = [n for n in ir.external_reads(program, sub.idx)
                     if parent._find_var_recursive(n) is not None]
        prior = [n for n in outs if n not in externals]
        parent.append_op(
            "conditional_block",
            inputs={"Cond": [eff.name], "X": externals + prior},
            outputs={"Out": outs},
            attrs={"sub_block": sub.idx, "out_vars": outs, "else_block": -1})

    def _effective_cond(self, parent, condition):
        from .. import unique_name

        def _logical(op_type, ins):
            name = unique_name.generate("switch_cond")
            v = parent.create_var(name=name, shape=(1,), dtype="bool",
                                  stop_gradient=True)
            parent.append_op(op_type, inputs=ins, outputs={"Out": [name]},
                             attrs={"axis": -1})
            return v

        none_prev = None
        for prev in self._prev_conds:
            none_prev = (prev if none_prev is None
                         else _logical("logical_or", {"X": [none_prev.name],
                                                      "Y": [prev.name]}))
        if none_prev is not None:
            none_prev = _logical("logical_not", {"X": [none_prev.name]})
        if condition is None:
            return none_prev if none_prev is not None else _always_true(parent)
        if none_prev is None:
            return condition
        return _logical("logical_and", {"X": [condition.name],
                                        "Y": [none_prev.name]})


def _always_true(block):
    from .. import unique_name
    name = unique_name.generate("switch_true")
    v = block.create_var(name=name, shape=(1,), dtype="bool", stop_gradient=True)
    block.append_op("fill_constant", outputs={"Out": [name]},
                    attrs={"shape": [1], "dtype": "bool", "value": 1.0})
    return v


def array_write(x, i, array=None):
    raise NotImplementedError(
        "tensor_array ops land with the DynamicRNN milestone; use StaticRNN "
        "or the scan-based dynamic_lstm/dynamic_gru layers")


def array_read(array, i):
    raise NotImplementedError(
        "tensor_array ops land with the DynamicRNN milestone")
