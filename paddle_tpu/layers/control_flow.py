"""Control-flow DSL: While / StaticRNN / Switch / increment / array ops.

Capability parity with reference python/paddle/fluid/layers/control_flow.py
(While :654, StaticRNN :429, Switch :1282, IfElse :1408, increment,
less_than, array_write/array_read). Sub-blocks become nested IR blocks and
lower to lax.while_loop / lax.scan / lax.cond (ops/control.py) — the
reference's nested-Executor StepScopes machinery has no TPU analog because
the loop never leaves the compiled program.
"""

from __future__ import annotations

import contextlib

from .. import unique_name
from ..core import ir
from ..layer_helper import LayerHelper
from . import tensor as lt


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out_name = x.name if in_place else None
    if out_name is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        out_name = out.name
    helper.append_op("increment", inputs={"X": [x.name]},
                     outputs={"Out": [out_name]}, attrs={"step": float(value)})
    return x if in_place else out


def less_than(x, y, cond=None):
    helper = LayerHelper("less_than")
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op("less_than", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [cond.name]}, attrs={"axis": -1})
    return cond


def equal(x, y, cond=None):
    helper = LayerHelper("equal")
    if cond is None:
        cond = helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op("equal", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [cond.name]}, attrs={"axis": -1})
    return cond


class While:
    """`with While(cond).block(): ...` loop (reference control_flow.py:654).

    The body must re-assign `cond` (via layers.assign / logical ops) so the
    loop terminates. All outer variables assigned inside the body become
    loop-carried state.
    """

    def __init__(self, cond, is_test=False, name=None, max_iters=None):
        """`max_iters` bounds the loop with a fixed-length masked scan so
        gradients flow through it (op `bounded_while`); without it the loop
        is a lax.while_loop, which is forward-only (reference while_grad,
        while_op.cc:96, is the analogous backward machinery)."""
        self.helper = LayerHelper("while", name=name)
        self.cond_var = cond
        self.max_iters = max_iters

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent_block = program.current_block()
        sub_block = program._create_block()
        yield
        program._rollback()

        # loop-carried state: vars written in the sub-block that exist in an
        # enclosing block (assign-out pattern), plus the condition.
        carry = []
        for op in sub_block.ops:
            for n in op.output_arg_names:
                if n in parent_block.vars or (
                        parent_block._find_var_recursive(n) is not None
                        and n not in sub_block.vars):
                    if n not in carry:
                        carry.append(n)
        if self.cond_var.name not in carry:
            carry.append(self.cond_var.name)
        x_inputs = sorted(set(ir.external_reads(program, sub_block.idx))
                          | set(carry))

        # SSA snapshot of the loop-carried state: the while op mutates its
        # carries in place, so a grad op re-tracing the loop later would read
        # POST-loop values (e.g. cond already false -> identity loop, wrong
        # grads). Copy each carry to a fresh `@PRE` var the op reads instead;
        # `assign`'s grad then routes carry grads back to the real producers
        # through the normal fan-in machinery.
        pre_map = {}
        for n in carry:
            pre = parent_block.create_var(
                name=unique_name.generate(f"{n}@PRE"),
                shape=parent_block._find_var_recursive(n).shape
                if parent_block._find_var_recursive(n) is not None else (),
                dtype=parent_block._find_var_recursive(n).dtype
                if parent_block._find_var_recursive(n) is not None
                else "float32")
            parent_block.append_op("assign", inputs={"X": [n]},
                                   outputs={"Out": [pre.name]})
            pre_map[n] = pre.name

        attrs = {"sub_block": sub_block.idx, "carry_vars": list(carry),
                 "cond_var": self.cond_var.name,
                 "carry_pre": {n: pre_map[n] for n in carry}}
        op_type = "while"
        if self.max_iters is not None:
            op_type = "bounded_while"
            attrs["max_iters"] = int(self.max_iters)
        x_ext = [n for n in x_inputs
                 if parent_block._find_var_recursive(n) is not None
                 and n not in pre_map]
        parent_block.append_op(
            op_type,
            inputs={"X": x_ext + [pre_map[n] for n in carry],
                    "Condition": [pre_map[self.cond_var.name]]},
            outputs={"Out": list(carry)},
            attrs=attrs)


class StaticRNN:
    """Scan-based RNN builder (reference control_flow.py:429).

    with rnn.step():
        x_t = rnn.step_input(x)       # [B, T, D] -> [B, D]
        h = rnn.memory(init=h0)       # carried state
        nh = some_layers(x_t, h)
        rnn.update_memory(h, nh)
        rnn.step_output(nh)
    outs = rnn()                      # [B, T, H]
    """

    def __init__(self, name=None, num_steps=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self.num_steps = num_steps  # for input-free (decode) loops
        self._step_inputs = []   # (outer_name, inner_name)
        self._memories = []      # (pre_name, mem_name, init_name)
        self._step_outputs = []  # inner names
        self._outputs = []       # outer Vars
        self._sub_block = None
        self._parent_block = None

    @contextlib.contextmanager
    def step(self):
        program = self.helper.main_program
        self._parent_block = program.current_block()
        self._sub_block = program._create_block()
        yield
        program._rollback()
        self._finalize()

    def step_input(self, x):
        inner = self._sub_block.create_var(
            name=f"{self.helper.name}.in_{len(self._step_inputs)}",
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype)
        self._step_inputs.append((x.name, inner.name))
        return inner

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        if init is None:
            if shape is None or batch_ref is None:
                raise ValueError("memory() needs `init` or (shape, batch_ref)")
            # build init in the PARENT block
            program = self.helper.main_program
            cur = program._current_block_idx
            program._current_block_idx = self._parent_block.idx
            try:
                from . import tensor as _t
                init = _t.fill_constant_batch_size_like(
                    batch_ref, [0] + list(shape[1:] if len(shape) > 1 else shape),
                    "float32", init_value, input_dim_idx=0, output_dim_idx=0)
            finally:
                program._current_block_idx = cur
        pre = self._sub_block.create_var(
            name=f"{self.helper.name}.mem_{len(self._memories)}",
            shape=init.shape, dtype=init.dtype)
        self._memories.append([pre.name, None, init.name])
        return pre

    def update_memory(self, mem, var):
        for m in self._memories:
            if m[0] == mem.name:
                m[1] = var.name
                return
        raise ValueError(f"{mem.name} is not a memory of this StaticRNN")

    def step_output(self, o):
        self._step_outputs.append(o.name)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _finalize(self):
        for m in self._memories:
            if m[1] is None:
                raise ValueError(f"memory {m[0]} was never update_memory()-ed")
        outs = []
        for inner_name in self._step_outputs:
            inner = self._sub_block.vars.get(inner_name)
            shape = ((inner.shape[0], -1) + tuple(inner.shape[1:])
                     if inner is not None and inner.shape else ())
            out = self._parent_block.create_var(
                name=f"{self.helper.name}.out_{len(outs)}",
                shape=shape, dtype=inner.dtype if inner else "float32")
            outs.append(out)
        self._outputs = outs
        program = self.helper.main_program
        externals = [n for n in ir.external_reads(program, self._sub_block.idx)
                     if self._parent_block._find_var_recursive(n) is not None]
        init_names = [m[2] for m in self._memories]
        x_names = [outer for outer, _ in self._step_inputs]
        all_ins = list(dict.fromkeys(x_names + init_names + externals))
        self._parent_block.append_op(
            "static_rnn",
            inputs={"X": all_ins},
            outputs={"Out": [o.name for o in outs]},
            attrs={"sub_block": self._sub_block.idx,
                   "step_inputs": [list(p) for p in self._step_inputs],
                   "memories": [list(m) for m in self._memories],
                   "step_outputs": list(self._step_outputs),
                   "num_steps": self.num_steps or 0})

    def __call__(self):
        if len(self._outputs) == 1:
            return self._outputs[0]
        return self._outputs


class Switch:
    """Reference control_flow.py:1282 — used mainly for LR warmup schedules.
    First matching case wins, as in the reference: each case's effective
    condition is `its condition AND none-of-the-previous`; the default fires
    only when every case condition was false. Each case lowers to a
    lax.cond over a sub-block.
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._prev_conds = []

    @contextlib.contextmanager
    def case(self, condition):
        yield from self._record(condition)

    @contextlib.contextmanager
    def default(self):
        yield from self._record(None)

    def _record(self, condition):
        program = self.helper.main_program
        parent = program.current_block()
        sub = program._create_block()
        yield
        program._rollback()
        outs = sorted({n for op in sub.ops for n in op.output_arg_names
                       if parent._find_var_recursive(n) is not None})
        eff = self._effective_cond(parent, condition)
        if condition is not None:
            self._prev_conds.append(condition)
        externals = [n for n in ir.external_reads(program, sub.idx)
                     if parent._find_var_recursive(n) is not None]
        prior = [n for n in outs if n not in externals]
        parent.append_op(
            "conditional_block",
            inputs={"Cond": [eff.name], "X": externals + prior},
            outputs={"Out": outs},
            attrs={"sub_block": sub.idx, "out_vars": outs, "else_block": -1})

    def _effective_cond(self, parent, condition):
        from .. import unique_name

        def _logical(op_type, ins):
            name = unique_name.generate("switch_cond")
            v = parent.create_var(name=name, shape=(1,), dtype="bool",
                                  stop_gradient=True)
            parent.append_op(op_type, inputs=ins, outputs={"Out": [name]},
                             attrs={"axis": -1})
            return v

        none_prev = None
        for prev in self._prev_conds:
            none_prev = (prev if none_prev is None
                         else _logical("logical_or", {"X": [none_prev.name],
                                                      "Y": [prev.name]}))
        if none_prev is not None:
            none_prev = _logical("logical_not", {"X": [none_prev.name]})
        if condition is None:
            return none_prev if none_prev is not None else _always_true(parent)
        if none_prev is None:
            return condition
        return _logical("logical_and", {"X": [condition.name],
                                        "Y": [none_prev.name]})


def _always_true(block):
    from .. import unique_name
    name = unique_name.generate("switch_true")
    v = block.create_var(name=name, shape=(1,), dtype="bool", stop_gradient=True)
    block.append_op("fill_constant", outputs={"Out": [name]},
                    attrs={"shape": [1], "dtype": "bool", "value": 1.0})
    return v


# ---------------------------------------------------------------------------
# Tensor arrays (reference: layers/control_flow.py array_write :1030,
# array_read :1120, array_length :1190, tensor_array_read_write_op.cc).
# A tensor array is a pre-allocated [capacity, ...] device buffer plus an
# `@ALEN` int32 length companion — see ops/tensor_array.py for the redesign
# rationale (XLA static shapes forbid the reference's growing host vector).
# ---------------------------------------------------------------------------

ALEN_SUFFIX = "@ALEN"


def _alen_var(block, array):
    name = array.name + ALEN_SUFFIX
    if name in block.vars:
        return block.vars[name]
    return block.create_var(name=name, shape=(), dtype="int32",
                            stop_gradient=True)


def create_array(dtype="float32", capacity=None):
    """Declare a tensor-array variable (reference create_array). `capacity`
    bounds the number of entries (static buffer size); defaults to
    ops.tensor_array.DEFAULT_ARRAY_CAPACITY at first write."""
    helper = LayerHelper("array")
    arr = helper.block.create_var(
        name=unique_name.generate("array"), shape=(), dtype=dtype)
    arr.is_tensor_array = True
    arr.array_capacity = capacity
    arr.array_written = False
    return arr


def array_write(x, i, array=None, capacity=None):
    """Write x into array[i]; returns the array (reference :1030)."""
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(dtype=x.dtype, capacity=capacity)
    block = helper.block
    alen = _alen_var(block, array)
    inputs = {"X": [x.name], "I": [i.name]}
    written = getattr(array, "array_written", True)
    if written:
        inputs["Array"] = [array.name]
        inputs["ALen"] = [alen.name]
    cap = capacity or getattr(array, "array_capacity", None)
    attrs = {"capacity": int(cap)} if cap else {}
    helper.append_op("array_write", inputs=inputs,
                     outputs={"Out": [array.name], "OutLen": [alen.name]},
                     attrs=attrs)
    array.array_written = True
    return array


def array_read(array, i):
    """Read array[i] (reference :1120)."""
    helper = LayerHelper("array_read")
    out = helper.create_variable_for_type_inference(dtype=array.dtype)
    helper.append_op("array_read",
                     inputs={"Array": [array.name], "I": [i.name]},
                     outputs={"Out": [out.name]})
    return out


def array_length(array):
    """Logical length of the array (reference :1190)."""
    helper = LayerHelper("array_length")
    alen = _alen_var(helper.block, array)
    out = helper.create_variable_for_type_inference(dtype="int32")
    out.stop_gradient = True
    helper.append_op("array_length", inputs={"ALen": [alen.name]},
                     outputs={"Out": [out.name]})
    return out


def lod_rank_table(x, level=0):
    """Sequence rank table (reference lod_rank_table :828). On the padded
    representation this is the row-lengths vector (ops/tensor_array.py)."""
    helper = LayerHelper("lod_rank_table")
    inputs = {"X": [x.name]}
    seq = helper.ensure_seqlen_var(x)
    if seq is not None:
        inputs["SeqLen"] = [seq.name]
    out = helper.create_variable_for_type_inference(dtype="int32")
    out.stop_gradient = True
    helper.append_op("lod_rank_table", inputs=inputs,
                     outputs={"Out": [out.name]})
    return out


def max_sequence_len(rank_table):
    """Max length in a rank table (reference max_sequence_len :895)."""
    helper = LayerHelper("max_seqence_len")
    out = helper.create_variable_for_type_inference(dtype="int32")
    out.stop_gradient = True
    helper.append_op("max_sequence_len", inputs={"RankTable": [rank_table.name]},
                     outputs={"Out": [out.name]})
    return out


def lod_tensor_to_array(x, table):
    """[B,T,...] LoD tensor -> time-major tensor array (reference :925)."""
    helper = LayerHelper("lod_tensor_to_array")
    array = create_array(dtype=x.dtype)
    alen = _alen_var(helper.block, array)
    helper.append_op("lod_tensor_to_array",
                     inputs={"X": [x.name], "RankTable": [table.name]},
                     outputs={"Out": [array.name], "OutLen": [alen.name]})
    array.array_written = True
    return array


def array_to_lod_tensor(x, table):
    """Tensor array -> [B,T,...] LoD tensor with lengths restored (:975)."""
    helper = LayerHelper("array_to_lod_tensor")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out.lod_level = 1
    helper.append_op("array_to_lod_tensor",
                     inputs={"X": [x.name], "RankTable": [table.name]},
                     outputs={"Out": [out.name]})
    return out


def shrink_memory(x, i, table):
    """Freeze finished rows at step i (reference shrink_rnn_memory_op.cc);
    masked-select analog — see ops/tensor_array.py."""
    helper = LayerHelper("shrink_memory")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("shrink_memory",
                     inputs={"X": [x.name], "I": [i.name],
                             "RankTable": [table.name]},
                     outputs={"Out": [out.name]})
    return out


def reorder_lod_tensor_by_rank(x, rank_table):
    helper = LayerHelper("reorder_lod_tensor_by_rank")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    idx = helper.create_variable_for_type_inference(dtype="int32")
    idx.stop_gradient = True
    helper.append_op("reorder_lod_tensor_by_rank",
                     inputs={"X": [x.name], "RankTable": [rank_table.name]},
                     outputs={"Out": [out.name], "OutIndex": [idx.name]})
    return out


class DynamicRNN:
    """Variable-length RNN builder (reference control_flow.py:1538).

    with rnn.block():
        x_t = rnn.step_input(seq)          # [B,T,D] lod var -> [B,D]
        h = rnn.memory(shape=[H], value=0) # carried, frozen past row length
        nh = some_layers(x_t, h)
        rnn.update_memory(h, nh)
        rnn.output(nh)
    out = rnn()                            # [B,T,H] lod var

    Lowered to ONE masked lax.scan (op `dynamic_rnn`, ops/control.py) instead
    of the reference's lod_rank_table/while/shrink_rnn_memory pipeline —
    identical numerics on the padded representation.
    """

    BEFORE_RNN = 0
    IN_RNN = 1
    AFTER_RNN = 2

    def __init__(self, name=None):
        self.helper = LayerHelper("dynamic_rnn", name=name)
        self.status = DynamicRNN.BEFORE_RNN
        self._step_inputs = []   # (outer_name, inner_name)
        self._static_inputs = []
        self._memories = []      # [pre_name, mem_name or None, init_name]
        self._step_outputs = []
        self._outputs = []
        self._sub_block = None
        self._parent_block = None
        self._seq_var = None     # first step_input's outer var (for lengths)

    @contextlib.contextmanager
    def block(self):
        if self.status != DynamicRNN.BEFORE_RNN:
            raise ValueError("rnn.block() can only be entered once")
        program = self.helper.main_program
        self._parent_block = program.current_block()
        self._sub_block = program._create_block()
        self.status = DynamicRNN.IN_RNN
        yield
        program._rollback()
        self.status = DynamicRNN.AFTER_RNN
        self._finalize()

    def step_input(self, x, level=0):
        self._assert_in_rnn("step_input")
        if self._seq_var is None:
            self._seq_var = x
        inner = self._sub_block.create_var(
            name=f"{self.helper.name}.in_{len(self._step_inputs)}",
            shape=(x.shape[0],) + tuple(x.shape[2:]), dtype=x.dtype)
        self._step_inputs.append((x.name, inner.name))
        return inner

    def static_input(self, x):
        """A var visible unchanged at every step (reference :1636) — with
        whole-batch masking no reorder is needed; the var is simply read."""
        self._assert_in_rnn("static_input")
        self._static_inputs.append(x.name)
        return x

    def memory(self, init=None, shape=None, value=0.0, need_reorder=False,
               dtype="float32"):
        self._assert_in_rnn("memory")
        if init is None:
            if shape is None:
                raise ValueError("memory() needs `init` or `shape`")
            if self._seq_var is None:
                raise ValueError("call step_input() before shape-based memory()")
            program = self.helper.main_program
            cur = program._current_block_idx
            program._current_block_idx = self._parent_block.idx
            try:
                init = lt.fill_constant_batch_size_like(
                    self._seq_var, [-1] + list(shape), dtype, value,
                    input_dim_idx=0, output_dim_idx=0)
            finally:
                program._current_block_idx = cur
        pre = self._sub_block.create_var(
            name=f"{self.helper.name}.mem_{len(self._memories)}",
            shape=init.shape, dtype=init.dtype)
        self._memories.append([pre.name, None, init.name])
        return pre

    def update_memory(self, ex_mem, new_mem):
        self._assert_in_rnn("update_memory")
        for m in self._memories:
            if m[0] == ex_mem.name:
                m[1] = new_mem.name
                return
        raise ValueError(f"{ex_mem.name} is not a memory of this DynamicRNN")

    def output(self, *outputs):
        self._assert_in_rnn("output")
        for o in outputs:
            self._step_outputs.append(o.name)

    def _assert_in_rnn(self, method):
        if self.status != DynamicRNN.IN_RNN:
            raise ValueError(f"{method}() must be called inside rnn.block()")

    def _finalize(self):
        if not self._step_inputs:
            raise ValueError("DynamicRNN needs at least one step_input")
        for m in self._memories:
            if m[1] is None:
                raise ValueError(f"memory {m[0]} was never update_memory()-ed")
        if not self._step_outputs:
            raise ValueError("DynamicRNN needs at least one output")
        program = self.helper.main_program
        outs = []
        for inner_name in self._step_outputs:
            inner = self._sub_block.vars.get(inner_name)
            shape = ((inner.shape[0], -1) + tuple(inner.shape[1:])
                     if inner is not None and inner.shape else ())
            out = self._parent_block.create_var(
                name=f"{self.helper.name}.out_{len(outs)}",
                shape=shape, dtype=inner.dtype if inner else "float32")
            out.lod_level = 1
            outs.append(out)
        self._outputs = outs
        externals = [n for n in ir.external_reads(program, self._sub_block.idx)
                     if self._parent_block._find_var_recursive(n) is not None]
        init_names = [m[2] for m in self._memories]
        x_names = [outer for outer, _ in self._step_inputs]
        all_ins = list(dict.fromkeys(x_names + init_names
                                     + self._static_inputs + externals))
        inputs = {"X": all_ins}
        from ..core.ir import seqlen_var_name
        seq_name = seqlen_var_name(self._seq_var.name)
        if self._seq_var.lod_level > 0:
            blk = self._seq_var.block
            if seq_name not in blk.vars:
                blk.create_var(name=seq_name, shape=(-1,), dtype="int32",
                               stop_gradient=True)
            inputs["SeqLen"] = [seq_name]
        self._parent_block.append_op(
            "dynamic_rnn",
            inputs=inputs,
            outputs={"Out": [o.name for o in outs],
                     "OutLen": [seqlen_var_name(o.name) for o in outs]},
            attrs={"sub_block": self._sub_block.idx,
                   "step_inputs": [list(p) for p in self._step_inputs],
                   "memories": [list(m) for m in self._memories],
                   "step_outputs": list(self._step_outputs)})
        for o in outs:
            if seqlen_var_name(o.name) not in self._parent_block.vars:
                self._parent_block.create_var(
                    name=seqlen_var_name(o.name), shape=(-1,), dtype="int32",
                    stop_gradient=True)

    def __call__(self):
        if self.status != DynamicRNN.AFTER_RNN:
            raise ValueError("DynamicRNN outputs are available after block()")
        if len(self._outputs) == 1:
            return self._outputs[0]
        return self._outputs


class IfElse:
    """Per-row two-way branch (reference control_flow.py:1408).

    ie = IfElse(cond)           # cond: [B,1] bool
    with ie.true_block():
        x_t = ie.input(x)
        ie.output(f(x_t))
    with ie.false_block():
        ie.output(g(ie.input(x)))
    out, = ie()

    Reference splits the batch by mask, runs each branch on its slice, and
    merges; here both branches run on the full batch and rows are selected
    with `where` (op `if_else`, ops/control.py) — SPMD-friendly, no dynamic
    shapes, same results for the row-local compute the API supports.
    """

    def __init__(self, cond, name=None):
        self.helper = LayerHelper("ifelse", name=name)
        self.cond = cond
        self._blocks = {}        # "true"/"false" -> sub_block
        self._outs = {"true": [], "false": []}
        self._inputs = []
        self._current = None

    @contextlib.contextmanager
    def true_block(self):
        yield from self._branch("true")

    @contextlib.contextmanager
    def false_block(self):
        yield from self._branch("false")

    def _branch(self, which):
        program = self.helper.main_program
        self._parent_block = program.current_block()
        sub = program._create_block()
        self._blocks[which] = sub
        self._current = which
        yield
        program._rollback()
        self._current = None

    def input(self, x):
        if self._current is None:
            raise ValueError("input() must be called inside a branch block")
        if x.name not in self._inputs:
            self._inputs.append(x.name)
        return x

    def output(self, *outs):
        if self._current is None:
            raise ValueError("output() must be called inside a branch block")
        self._outs[self._current].extend(o.name for o in outs)

    def __call__(self):
        if "true" not in self._blocks or "false" not in self._blocks:
            raise ValueError("IfElse needs both true_block and false_block")
        nt, nf = len(self._outs["true"]), len(self._outs["false"])
        if nt != nf:
            raise ValueError(
                f"true_block produced {nt} outputs, false_block {nf}; they "
                f"must match")
        program = self.helper.main_program
        parent = program.current_block()
        externals = []
        for which in ("true", "false"):
            for n in ir.external_reads(program, self._blocks[which].idx):
                if parent._find_var_recursive(n) is not None \
                        and n not in externals:
                    externals.append(n)
        outs = []
        for tn in self._outs["true"]:
            inner = self._blocks["true"].vars.get(tn)
            out = parent.create_var(
                name=f"{self.helper.name}.out_{len(outs)}",
                shape=tuple(inner.shape) if inner is not None else (),
                dtype=inner.dtype if inner is not None else "float32")
            outs.append(out)
        parent.append_op(
            "if_else",
            inputs={"Cond": [self.cond.name], "X": externals},
            outputs={"Out": [o.name for o in outs]},
            attrs={"true_block": self._blocks["true"].idx,
                   "false_block": self._blocks["false"].idx,
                   "true_outs": list(self._outs["true"]),
                   "false_outs": list(self._outs["false"])})
        return outs


def Print(input, first_n=-1, message=None, summarize=-1,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """Runtime tensor printing (reference control_flow.py:143). Lowered to
    a host callback (jax.debug.print) firing from inside the compiled
    step; first_n/print_phase filtering is host-side cosmetics the
    callback cannot replicate exactly, so every access prints."""
    helper = LayerHelper("print")
    prefix = (message + " ") if message else ""
    if print_tensor_name:
        prefix += input.name + " "
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("print", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"message": prefix, "summarize": summarize})
    out.lod_level = input.lod_level
    return out


def is_empty(x, cond=None):
    """Whether `x` has zero elements (reference control_flow.py is_empty)."""
    helper = LayerHelper("is_empty")
    out = cond or helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op("is_empty", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]})
    return out


class ParallelDo:
    """Block-level data parallelism (reference parallel_do_op.cc:115,
    control_flow.py ParallelDo).

    TPU-native: the reference split the batch across places and ran the
    sub-block per device on threads; under GSPMD the WHOLE program is
    partitioned over the mesh, so the correct lowering of a parallel_do
    region is simply its body over the full batch — ParallelExecutor
    shards the batch dim and inserts the gradient all-reduce the
    reference's merge step performed (docs/RETIREMENT.md, P2->P1
    subsumption). This shim keeps source compatibility: do() traces the
    body inline; read_input/write_output are identity bookkeeping."""

    def __init__(self, places, use_nccl=False, name=None):
        self.helper = LayerHelper("parallel_do", name=name)
        self._inputs = []

    @contextlib.contextmanager
    def do(self):
        yield

    def read_input(self, var):
        self._inputs.append(var)
        return var

    def write_output(self, var):
        self._out = var

    def __call__(self):
        return self._out
