"""Operator overloading on Variable (reference:
python/paddle/fluid/layers/math_op_patch.py)."""

from __future__ import annotations

from ..core import ir
from ..layer_helper import LayerHelper


def _binary(op_type, reverse=False):
    def impl(self, other):
        from . import tensor as t
        helper = LayerHelper(op_type)
        if not isinstance(other, ir.Variable):
            # scalar -> fill_constant broadcastable tensor
            other = t.fill_constant([1], self.dtype, float(other))
        x, y = (other, self) if reverse else (self, other)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(op_type, inputs={"X": [x.name], "Y": [y.name]},
                         outputs={"Out": [out.name]}, attrs={"axis": -1})
        out.lod_level = max(self.lod_level, getattr(other, "lod_level", 0))
        return out

    return impl


def monkey_patch_variable():
    V = ir.Variable
    V.__add__ = _binary("elementwise_add")
    V.__radd__ = _binary("elementwise_add", reverse=True)
    V.__sub__ = _binary("elementwise_sub")
    V.__rsub__ = _binary("elementwise_sub", reverse=True)
    V.__mul__ = _binary("elementwise_mul")
    V.__rmul__ = _binary("elementwise_mul", reverse=True)
    V.__truediv__ = _binary("elementwise_div")
    V.__rtruediv__ = _binary("elementwise_div", reverse=True)
    V.__pow__ = _binary("elementwise_pow")
    V.__rpow__ = _binary("elementwise_pow", reverse=True)
    V.__mod__ = _binary("elementwise_mod")
    V.__lt__ = _binary("less_than")
    V.__le__ = _binary("less_equal")
    V.__gt__ = _binary("greater_than")
    V.__ge__ = _binary("greater_equal")
    V.__neg__ = lambda self: self * (-1.0)
