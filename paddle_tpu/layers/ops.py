"""Auto-generated thin layer wrappers for activation / elementwise ops
(reference: python/paddle/fluid/layers/ops.py via layer_function_generator.py)."""

from __future__ import annotations

import sys

from ..layer_helper import LayerHelper

_ACTIVATIONS = [
    "sigmoid", "logsigmoid", "exp", "relu", "tanh", "tanh_shrink", "softshrink",
    "sqrt", "rsqrt", "abs", "ceil", "floor", "cos", "sin", "round", "reciprocal",
    "square", "softplus", "softsign", "brelu", "leaky_relu", "soft_relu", "elu",
    "relu6", "pow", "swish", "hard_sigmoid", "thresholded_relu", "hard_shrink",
    "gelu", "log", "sign",
]

_ELEMENTWISE = [
    "elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div",
    "elementwise_max", "elementwise_min", "elementwise_pow", "elementwise_mod",
]


def _make_act(op_type):
    def layer(x, name=None, **attrs):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(op_type, inputs={"X": [x.name]},
                         outputs={"Out": [out.name]}, attrs=attrs)
        out.lod_level = x.lod_level
        return out

    layer.__name__ = op_type
    layer.__doc__ = f"Elementwise `{op_type}` activation (lowered to XLA, fused by the compiler)."
    return layer


def _make_elementwise(op_type):
    def layer(x, y, axis=-1, act=None, name=None):
        helper = LayerHelper(op_type, name=name, act=act)
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
        helper.append_op(op_type, inputs={"X": [x.name], "Y": [y.name]},
                         outputs={"Out": [out.name]}, attrs={"axis": axis})
        out.lod_level = max(x.lod_level, getattr(y, "lod_level", 0))
        return helper.append_activation(out)

    layer.__name__ = op_type
    layer.__doc__ = f"`{op_type}` with reference broadcast semantics (axis attr)."
    return layer


_mod = sys.modules[__name__]
for _name in _ACTIVATIONS:
    setattr(_mod, _name, _make_act(_name))
for _name in _ELEMENTWISE:
    setattr(_mod, _name, _make_elementwise(_name))

__all__ = _ACTIVATIONS + _ELEMENTWISE
