"""Layer wrappers for sampled/structured losses (reference: nn.py nce :3780,
hsigmoid :3877, linear_chain_crf, crf_decoding, warpctc, edit_distance)."""

from __future__ import annotations

from ..layer_helper import LayerHelper
from .. import initializer as init


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None):
    helper = LayerHelper("nce", **locals())
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr, [num_total_classes, dim],
                                input.dtype)
    inputs = {"Input": [input.name], "Label": [label.name], "Weight": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, [num_total_classes],
                                    input.dtype, is_bias=True)
        inputs["Bias"] = [b.name]
    if sample_weight is not None:
        inputs["SampleWeight"] = [sample_weight.name]
    cost = helper.create_variable_for_type_inference(dtype=input.dtype)
    sl = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                   stop_gradient=True)
    slab = helper.create_variable_for_type_inference(dtype="int32",
                                                     stop_gradient=True)
    helper.append_op("nce", inputs=inputs,
                     outputs={"Cost": [cost.name], "SampleLogits": [sl.name],
                              "SampleLabels": [slab.name]},
                     attrs={"num_total_classes": num_total_classes,
                            "num_neg_samples": num_neg_samples or 10})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    helper = LayerHelper("hierarchical_sigmoid", **locals())
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr, [num_classes - 1, dim], input.dtype)
    inputs = {"X": [input.name], "W": [w.name], "Label": [label.name]}
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, [num_classes - 1, 1],
                                    input.dtype, is_bias=True)
        inputs["Bias"] = [b.name]
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    pre = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                    stop_gradient=True)
    helper.append_op("hierarchical_sigmoid", inputs=inputs,
                     outputs={"Out": [out.name], "PreOut": [pre.name]},
                     attrs={"num_classes": num_classes})
    return out


def linear_chain_crf(input, label, param_attr=None):
    """input [B,T,N] emissions (lod-aware), label [B,T,1]."""
    helper = LayerHelper("linear_chain_crf", **locals())
    num_tags = input.shape[-1]
    trans = helper.create_parameter(
        param_attr, [num_tags + 2, num_tags], input.dtype,
        default_initializer=init.NormalInitializer(0.0, 0.1))
    inputs = {"Emission": [input.name], "Transition": [trans.name],
              "Label": [label.name]}
    seq = helper.ensure_seqlen_var(input)
    if seq is not None:
        inputs["SeqLen"] = [seq.name]
    ll = helper.create_variable_for_type_inference(dtype=input.dtype)
    alpha = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                      stop_gradient=True)
    ee = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                   stop_gradient=True)
    te = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                   stop_gradient=True)
    helper.append_op("linear_chain_crf", inputs=inputs,
                     outputs={"LogLikelihood": [ll.name], "Alpha": [alpha.name],
                              "EmissionExps": [ee.name],
                              "TransitionExps": [te.name]})
    return ll


def crf_decoding(input, param_attr, label=None):
    helper = LayerHelper("crf_decoding", **locals())
    trans_name = param_attr.name if hasattr(param_attr, "name") else param_attr
    inputs = {"Emission": [input.name], "Transition": [trans_name]}
    if label is not None:
        inputs["Label"] = [label.name]
    seq = helper.ensure_seqlen_var(input)
    if seq is not None:
        inputs["SeqLen"] = [seq.name]
    path = helper.create_variable_for_type_inference(dtype="int64",
                                                     stop_gradient=True)
    helper.append_op("crf_decoding", inputs=inputs,
                     outputs={"ViterbiPath": [path.name]})
    return path


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """input [B,T,C] logits; label [B,U]."""
    helper = LayerHelper("warpctc", **locals())
    inputs = {"Logits": [input.name], "Label": [label.name]}
    seq = helper.ensure_seqlen_var(input)
    if seq is not None:
        inputs["LogitsLen"] = [seq.name]
    elif input_length is not None:
        inputs["LogitsLen"] = [input_length.name]
    lseq = helper.ensure_seqlen_var(label)
    if lseq is not None:
        inputs["LabelLen"] = [lseq.name]
    elif label_length is not None:
        inputs["LabelLen"] = [label_length.name]
    loss = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("warpctc", inputs=inputs, outputs={"Loss": [loss.name]},
                     attrs={"blank": blank, "norm_by_times": norm_by_times})
    return loss


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    helper = LayerHelper("edit_distance", **locals())
    inputs = {"Hyps": [input.name], "Refs": [label.name]}
    seq = helper.ensure_seqlen_var(input)
    if seq is not None:
        inputs["HypsLen"] = [seq.name]
    elif input_length is not None:
        inputs["HypsLen"] = [input_length.name]
    lseq = helper.ensure_seqlen_var(label)
    if lseq is not None:
        inputs["RefsLen"] = [lseq.name]
    elif label_length is not None:
        inputs["RefsLen"] = [label_length.name]
    dist = helper.create_variable_for_type_inference(dtype="float32",
                                                     stop_gradient=True)
    num = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    helper.append_op("edit_distance", inputs=inputs,
                     outputs={"Out": [dist.name], "SequenceNum": [num.name]},
                     attrs={"normalized": normalized})
    return dist, num
