"""Metric layers (reference: python/paddle/fluid/layers/metric_op.py)."""

from __future__ import annotations

from ..layer_helper import LayerHelper
from .. import initializer as init
from . import nn


def accuracy(input, label, k=1, correct=None, total=None):
    """Top-k accuracy of `input` logits/probs vs integer `label`."""
    helper = LayerHelper("accuracy")
    topk_out, topk_indices = nn.topk(input, k=k)
    acc_out = helper.create_variable_for_type_inference(dtype="float32")
    correct = correct or helper.create_variable_for_type_inference(dtype="int32")
    total = total or helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op("accuracy",
                     inputs={"Out": [topk_out.name], "Indices": [topk_indices.name],
                             "Label": [label.name]},
                     outputs={"Accuracy": [acc_out.name], "Correct": [correct.name],
                              "Total": [total.name]})
    return acc_out


def auc(input, label, curve="ROC", num_thresholds=200, topk=1):
    """Streaming AUC (reference metric_op.py `auc`). Keeps positive/negative
    histogram state in persistable vars updated each step."""
    helper = LayerHelper("auc")
    stat_pos = helper.create_global_variable(
        shape=[num_thresholds + 1], dtype="float32", persistable=True)
    stat_neg = helper.create_global_variable(
        shape=[num_thresholds + 1], dtype="float32", persistable=True)
    for v in (stat_pos, stat_neg):
        helper.set_variable_initializer(v, init.ConstantInitializer(0.0))
    auc_out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op("auc",
                     inputs={"Predict": [input.name], "Label": [label.name],
                             "StatPos": [stat_pos.name], "StatNeg": [stat_neg.name]},
                     outputs={"AUC": [auc_out.name], "StatPosOut": [stat_pos.name],
                              "StatNegOut": [stat_neg.name]},
                     attrs={"num_thresholds": num_thresholds, "curve": curve})
    return auc_out, [stat_pos, stat_neg]
