"""Data-input layers (reference: python/paddle/fluid/layers/io.py)."""

from __future__ import annotations

from ..core import ir
from ..layer_helper import LayerHelper


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True):
    """Declare an input variable (reference io.py:35 `data`).

    With append_batch_size (default, as in the reference) a -1 batch dim is
    prepended. lod_level>0 declares a variable-length sequence input: feed a
    `(padded_array, lengths)` pair or let DataFeeder build it.
    """
    helper = LayerHelper("data", name=name)
    shape = list(shape)
    if lod_level > 0:
        # padded variable-length layout: one dynamic dim per LoD level
        # ([batch, time, *feature] at level 1; [batch, seqs, time, *feature]
        # at level 2). The reference's packed LoD shape [sum_T, *feature]
        # gains explicit (dynamic) dims on TPU.
        dyn = [-1] * lod_level
        shape = ([-1] + dyn + shape) if append_batch_size else (dyn + shape)
    elif append_batch_size:
        shape = [-1] + shape
    block = helper.main_program.current_block()
    if name in block.vars:
        v = block.vars[name]
    else:
        v = block.create_var(name=name, shape=shape, dtype=dtype,
                             lod_level=lod_level, stop_gradient=stop_gradient,
                             is_data=True)
    for lvl in range(lod_level):
        helper.ensure_seqlen_var(v, level=lvl)
    return v


# ---------------------------------------------------------------------------
# reader-layer surface (reference io.py exposes the reader stack here; the
# implementations live in paddle_tpu.reader / paddle_tpu.recordio /
# paddle_tpu.pserver and are re-surfaced under the reference names)
# ---------------------------------------------------------------------------

def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Blocking-queue reader + its feed vars (reference io.py:449)."""
    from ..reader.py_reader import py_reader as _impl
    return _impl(capacity, shapes, dtypes, lod_levels=lod_levels, name=name,
                 use_double_buffer=use_double_buffer)


def open_recordio_file(filename, shapes, dtypes, lod_levels=None,
                       pass_num=1, for_parallel=True):
    """RecordIO-backed reader (reference io.py:320): returns a PyReader
    whose producer scans the file; records are pickled per-var tuples as
    written by paddle_tpu.recordio + DataFeeder (see tests/test_data_plane
    for the end-to-end train-from-recordio cycle)."""
    import pickle
    from .. import recordio as rio
    from ..reader.py_reader import py_reader as _impl

    reader, feed_vars = _impl(capacity=64, shapes=shapes, dtypes=dtypes,
                              lod_levels=lod_levels)

    def scan():
        for _ in range(pass_num):
            batch = []
            for rec in rio.reader(filename)():
                batch.append(pickle.loads(rec))
                if len(batch) == 16:
                    yield batch
                    batch = []
            if batch:
                yield batch

    reader.decorate_paddle_reader(scan)
    return reader, feed_vars


def double_buffer(reader, place=None, name=None):
    """Double-buffering decorator (reference io.py:866). PyReader already
    double-buffers (device pre-placement in its producer design); for plain
    python readers this wraps them in a buffered prefetch."""
    from ..reader import decorator as dec
    if hasattr(reader, "decorate_paddle_reader"):
        return reader            # PyReader: already double-buffered
    return dec.buffered(reader, 2)


def ListenAndServ(endpoint, inputs=None, fan_in=1, optimizer_mode=True):
    """Parameter-server serving loop (reference io.py:114). TPU-native: the
    host ParameterServer runtime (paddle_tpu/pserver/server.py) IS the
    listen-and-serv op — this shim starts it on `endpoint` and returns the
    server handle (stop() to shut down). Program-embedded server sub-blocks
    are retired: see docs/RETIREMENT.md."""
    from ..pserver import ParameterServer
    return ParameterServer(endpoint).start()


_ps_clients = {}


def _ps_client(endpoint):
    """One cached PSClient (socket + pool) per endpoint — Send/Recv are
    called per training step; constructing a client per call would leak a
    socket and a thread pool each step."""
    from ..pserver import PSClient
    if endpoint not in _ps_clients:
        _ps_clients[endpoint] = PSClient([endpoint])
    return _ps_clients[endpoint]


def Send(endpoint, var_names, scope=None, sync=True):
    """Push variables to a pserver (reference io.py:209 Send). Dense push
    via the PSClient gRPC-analog protocol."""
    import numpy as np
    from ..core.executor import global_scope
    scope = scope or global_scope()
    c = _ps_client(endpoint)
    for n in (var_names if isinstance(var_names, (list, tuple)) else [var_names]):
        val = scope.find_var(n)
        if val is None:
            raise KeyError(f"Send: variable {n!r} not found in scope")
        # grads are sent under their parameter's name (the reference's
        # transpiler maps w@GRAD slices onto the pserver-side param block)
        target = n[:-len("@GRAD")] if n.endswith("@GRAD") else n
        c.push_grad(endpoint, target, np.asarray(val))


def Recv(endpoint, var_names, scope=None, sync=True):
    """Fetch variables from a pserver (reference io.py:241 Recv)."""
    from ..core.executor import global_scope
    scope = scope or global_scope()
    c = _ps_client(endpoint)
    out = []
    for n in (var_names if isinstance(var_names, (list, tuple)) else [var_names]):
        val = c.get_param(endpoint, n)
        scope.set_var(n, val)
        out.append(val)
    return out
