"""Data-input layers (reference: python/paddle/fluid/layers/io.py)."""

from __future__ import annotations

from ..core import ir
from ..layer_helper import LayerHelper


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True):
    """Declare an input variable (reference io.py:35 `data`).

    With append_batch_size (default, as in the reference) a -1 batch dim is
    prepended. lod_level>0 declares a variable-length sequence input: feed a
    `(padded_array, lengths)` pair or let DataFeeder build it.
    """
    helper = LayerHelper("data", name=name)
    shape = list(shape)
    if lod_level > 0:
        # padded variable-length layout: [batch, time, *feature]. The
        # reference's packed LoD shape [sum_T, *feature] gains an explicit
        # (dynamic) time dim on TPU.
        shape = [-1, -1] + shape if append_batch_size else [-1] + shape
    elif append_batch_size:
        shape = [-1] + shape
    block = helper.main_program.current_block()
    if name in block.vars:
        v = block.vars[name]
    else:
        v = block.create_var(name=name, shape=shape, dtype=dtype,
                             lod_level=lod_level, stop_gradient=stop_gradient,
                             is_data=True)
    if lod_level > 0:
        helper.ensure_seqlen_var(v)
    return v
