"""Data-input layers (reference: python/paddle/fluid/layers/io.py)."""

from __future__ import annotations

from ..core import ir
from ..layer_helper import LayerHelper


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True,
         stop_gradient=True):
    """Declare an input variable (reference io.py:35 `data`).

    With append_batch_size (default, as in the reference) a -1 batch dim is
    prepended. lod_level>0 declares a variable-length sequence input: feed a
    `(padded_array, lengths)` pair or let DataFeeder build it.
    """
    helper = LayerHelper("data", name=name)
    shape = list(shape)
    if lod_level > 0:
        # padded variable-length layout: one dynamic dim per LoD level
        # ([batch, time, *feature] at level 1; [batch, seqs, time, *feature]
        # at level 2). The reference's packed LoD shape [sum_T, *feature]
        # gains explicit (dynamic) dims on TPU.
        dyn = [-1] * lod_level
        shape = ([-1] + dyn + shape) if append_batch_size else (dyn + shape)
    elif append_batch_size:
        shape = [-1] + shape
    block = helper.main_program.current_block()
    if name in block.vars:
        v = block.vars[name]
    else:
        v = block.create_var(name=name, shape=shape, dtype=dtype,
                             lod_level=lod_level, stop_gradient=stop_gradient,
                             is_data=True)
    for lvl in range(lod_level):
        helper.ensure_seqlen_var(v, level=lvl)
    return v


# ---------------------------------------------------------------------------
# reader-layer surface (reference io.py exposes the reader stack here; the
# implementations live in paddle_tpu.reader / paddle_tpu.recordio /
# paddle_tpu.pserver and are re-surfaced under the reference names)
# ---------------------------------------------------------------------------

def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    """Blocking-queue reader + its feed vars (reference io.py:449)."""
    from ..reader.py_reader import py_reader as _impl
    return _impl(capacity, shapes, dtypes, lod_levels=lod_levels, name=name,
                 use_double_buffer=use_double_buffer)


def open_recordio_file(filename, shapes, dtypes, lod_levels=None,
                       pass_num=1, for_parallel=True):
    """RecordIO-backed reader (reference io.py:320): returns a PyReader
    whose producer scans the file; records are pickled per-var tuples as
    written by paddle_tpu.recordio + DataFeeder (see tests/test_data_plane
    for the end-to-end train-from-recordio cycle)."""
    import pickle
    from .. import recordio as rio
    from ..reader.py_reader import py_reader as _impl

    reader, feed_vars = _impl(capacity=64, shapes=shapes, dtypes=dtypes,
                              lod_levels=lod_levels)

    def scan():
        for _ in range(pass_num):
            batch = []
            for rec in rio.reader(filename)():
                batch.append(pickle.loads(rec))
                if len(batch) == 16:
                    yield batch
                    batch = []
            if batch:
                yield batch

    reader.decorate_paddle_reader(scan)
    return reader, feed_vars


def double_buffer(reader, place=None, name=None):
    """Double-buffering decorator (reference io.py:866). PyReader already
    double-buffers (device pre-placement in its producer design); for plain
    python readers this wraps them in a buffered prefetch."""
    from ..reader import decorator as dec
    if hasattr(reader, "decorate_paddle_reader"):
        return reader            # PyReader: already double-buffered
    return dec.buffered(reader, 2)


def ListenAndServ(endpoint, inputs=None, fan_in=1, optimizer_mode=True):
    """Parameter-server serving loop (reference io.py:114). TPU-native: the
    host ParameterServer runtime (paddle_tpu/pserver/server.py) IS the
    listen-and-serv op — this shim starts it on `endpoint` and returns the
    server handle (stop() to shut down). Program-embedded server sub-blocks
    are retired: see docs/RETIREMENT.md."""
    from ..pserver import ParameterServer
    return ParameterServer(endpoint).start()


_ps_clients = {}


def _ps_client(endpoint):
    """One cached PSClient (socket + pool) per endpoint — Send/Recv are
    called per training step; constructing a client per call would leak a
    socket and a thread pool each step."""
    from ..pserver import PSClient
    if endpoint not in _ps_clients:
        _ps_clients[endpoint] = PSClient([endpoint])
    return _ps_clients[endpoint]


def Send(endpoint, var_names, scope=None, sync=True):
    """Push variables to a pserver (reference io.py:209 Send). Dense push
    via the PSClient gRPC-analog protocol."""
    import numpy as np
    from ..core.executor import global_scope
    scope = scope or global_scope()
    c = _ps_client(endpoint)
    for n in (var_names if isinstance(var_names, (list, tuple)) else [var_names]):
        val = scope.find_var(n)
        if val is None:
            raise KeyError(f"Send: variable {n!r} not found in scope")
        # grads are sent under their parameter's name (the reference's
        # transpiler maps w@GRAD slices onto the pserver-side param block)
        target = n[:-len("@GRAD")] if n.endswith("@GRAD") else n
        c.push_grad(endpoint, target, np.asarray(val))


def Recv(endpoint, var_names, scope=None, sync=True):
    """Fetch variables from a pserver (reference io.py:241 Recv)."""
    from ..core.executor import global_scope
    scope = scope or global_scope()
    c = _ps_client(endpoint)
    out = []
    for n in (var_names if isinstance(var_names, (list, tuple)) else [var_names]):
        val = c.get_param(endpoint, n)
        scope.set_var(n, val)
        out.append(val)
    return out


def read_file(reader):
    """Pop the reader's output variables (reference io.py read_file). Feeds
    are explicit in this executor design — the PyReader's feed vars ARE
    the read results, armed to pop from the blocking queue on each run."""
    if isinstance(reader, (tuple, list)):
        reader, feed_vars = reader
    else:
        feed_vars = reader.feed_vars
    return feed_vars if len(feed_vars) > 1 else feed_vars[0]


def shuffle(reader, buffer_size):
    """Shuffling reader decorator surfaced at the layers level (reference
    io.py shuffle, which wrapped an in-graph reader; the in-graph reader
    tree is subsumed by python readers + py_reader, docs/RETIREMENT.md)."""
    from ..reader import decorator as dec
    return dec.shuffle(reader, buffer_size)


def batch(reader, batch_size):
    """Batching reader decorator at the layers level (reference io.py
    batch -> create_batch_reader). Keeps the final partial batch like the
    reference; pass drop_last=True via reader.decorator.batch when static
    batch shapes matter (avoids one extra jit per tail shape)."""
    from ..reader import decorator as dec
    return dec.batch(reader, batch_size, drop_last=False)


def open_files(filenames, shapes, dtypes, lod_levels=None, thread_num=1,
               buffer_size=None, pass_num=1, for_parallel=True):
    """Multi-file RecordIO reader (reference io.py:699 open_files):
    round-robin scan of the files feeding one blocking queue."""
    import pickle
    from .. import recordio as rio
    from ..reader.py_reader import py_reader as _impl

    reader, feed_vars = _impl(capacity=buffer_size or 64, shapes=shapes,
                              dtypes=dtypes, lod_levels=lod_levels)

    def scan():
        for _ in range(pass_num):
            batch_ = []
            for fn in filenames:
                for rec in rio.reader(fn)():
                    batch_.append(pickle.loads(rec))
                    if len(batch_) == 16:
                        yield batch_
                        batch_ = []
            if batch_:
                yield batch_

    reader.decorate_paddle_reader(scan)
    return reader, feed_vars


def random_data_generator(low, high, shapes, lod_levels=None, for_parallel=True):
    """Uniform-random python reader (reference io.py random_data_generator,
    used by reader-op tests): yields tuples of float32 arrays."""
    import numpy as np

    def reader():
        rng = np.random.RandomState(0)
        while True:
            yield tuple(rng.uniform(low, high, s).astype(np.float32)
                        for s in shapes)

    return reader


def load(out, file_path, load_as_fp16=None):
    """Load a saved array into `out` at run time (reference io.py load ->
    load op). The file is one np.save'd array as written by
    paddle_tpu.io.save_vars(save_separately)."""
    helper = LayerHelper("load")
    helper.append_op("load", outputs={"Out": [out.name]},
                     attrs={"file_path": file_path,
                            "load_as_fp16": bool(load_as_fp16)})
    return out


class Preprocessor:
    """In-graph batch preprocessing (reference io.py:943 Preprocessor).

    The user declares the preprocessing body with regular layers inside
    `.block()`; the body is captured as its own mini Program and jit-run
    on each batch popped from the source reader — the TPU analog of the
    reference's create_custom_reader sub-block."""

    def __init__(self, reader, name=None):
        self._source = reader
        self._in_vars = None
        self._out_vars = None
        self._program = None
        self._startup = None

    def inputs(self, dtypes, shapes):
        assert self._program is not None, "call inside .block()"
        self._in_vars = [
            data(name=f"_preproc_in_{i}", shape=list(s), dtype=d,
                 append_batch_size=False)
            for i, (d, s) in enumerate(zip(dtypes, shapes))]
        return self._in_vars

    def outputs(self, *outs):
        self._out_vars = list(outs)

    def block(self):
        import contextlib
        from .. import program_guard, Program, unique_name

        @contextlib.contextmanager
        def guard():
            self._program, self._startup = Program(), Program()
            with program_guard(self._program, self._startup), \
                    unique_name.guard():
                yield self
        return guard()

    def __call__(self):
        from ..core.executor import Executor, CPUPlace, Scope
        assert self._in_vars and self._out_vars, \
            "Preprocessor.block() must declare inputs() and outputs()"
        exe = Executor(CPUPlace())
        scope = Scope()
        exe.run(self._startup, scope=scope)

        def reader():
            for item in self._source():
                feed = {v.name: arr for v, arr in zip(self._in_vars, item)}
                yield tuple(exe.run(self._program, feed=feed,
                                    fetch_list=self._out_vars, scope=scope))
        return reader
