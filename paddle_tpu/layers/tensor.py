"""Tensor-manipulation layers (reference: python/paddle/fluid/layers/tensor.py)."""

from __future__ import annotations

from ..core import ir
from ..layer_helper import LayerHelper


def _single_out(helper, op_type, inputs, attrs=None, dtype=None, out_slot="Out",
                lod_from=None):
    dtype = dtype or "float32"
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(op_type, inputs=inputs, outputs={out_slot: [out.name]},
                     attrs=attrs or {})
    if lod_from is not None and isinstance(lod_from, ir.Variable):
        out.lod_level = lod_from.lod_level
    return out


def create_tensor(dtype="float32", name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.main_program.current_block().create_var(
        name=name, dtype=dtype, shape=(), persistable=persistable)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    from .. import initializer as init
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(name=name, shape=shape, dtype=dtype,
                                        persistable=persistable)
    helper.set_variable_initializer(var, init.ConstantInitializer(value))
    return var


def fill_constant(shape, dtype, value, out=None, name=None):
    helper = LayerHelper("fill_constant", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op("fill_constant", outputs={"Out": [out.name]},
                     attrs={"shape": list(shape), "dtype": dtype, "value": float(value)})
    return out


def fill_constant_batch_size_like(input, shape, dtype, value, input_dim_idx=0,
                                  output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op("fill_constant_batch_size_like",
                     inputs={"Input": [input.name]}, outputs={"Out": [out.name]},
                     attrs={"shape": list(shape), "dtype": dtype, "value": float(value),
                            "input_dim_idx": input_dim_idx,
                            "output_dim_idx": output_dim_idx})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    import numpy as np
    if isinstance(input, ir.Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=input.dtype)
        helper.append_op("assign", inputs={"X": [input.name]},
                         outputs={"Out": [output.name]})
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(dtype=str(arr.dtype))
        helper.append_op("assign_value", outputs={"Out": [output.name]},
                         attrs={"shape": list(arr.shape), "dtype": str(arr.dtype),
                                "values": [float(v) for v in arr.reshape(-1)]})
    return output


def cast(x, dtype):
    helper = LayerHelper("cast")
    return _single_out(helper, "cast", {"X": [x.name]}, {"out_dtype": str(dtype)},
                       dtype=str(dtype), lod_from=x)


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    names = [v.name for v in input]
    return _single_out(helper, "concat", {"X": names}, {"axis": axis},
                       dtype=input[0].dtype, lod_from=input[0])


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op("sum", inputs={"X": [v.name for v in input]},
                     outputs={"Out": [out.name]})
    return out


def argmax(x, axis=0):
    helper = LayerHelper("arg_max")
    return _single_out(helper, "arg_max", {"X": [x.name]}, {"axis": axis},
                       dtype="int64")


def argmin(x, axis=0):
    helper = LayerHelper("arg_min")
    return _single_out(helper, "arg_min", {"X": [x.name]}, {"axis": axis},
                       dtype="int64")


def argsort(x, axis=-1, name=None):
    """Sorted values + indices (reference tensor.py argsort)."""
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    ids = helper.create_variable_for_type_inference(dtype="int64")
    helper.append_op("argsort", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "Indices": [ids.name]},
                     attrs={"axis": axis})
    return out, ids


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Create a learnable Parameter directly (reference tensor.py
    create_parameter) — same path fc/conv use via LayerHelper."""
    from ..param_attr import ParamAttr
    helper = LayerHelper("create_parameter", name=name)
    attr = attr or ParamAttr(name=name)
    return helper.create_parameter(attr, shape, dtype, is_bias=is_bias,
                                   default_initializer=default_initializer)


def zeros(shape, dtype="float32"):
    return fill_constant(shape, dtype, 0.0)


def ones(shape, dtype="float32"):
    return fill_constant(shape, dtype, 1.0)


def reverse(x, axis):
    helper = LayerHelper("reverse")
    axis = [axis] if isinstance(axis, int) else list(axis)
    return _single_out(helper, "reverse", {"X": [x.name]}, {"axis": axis},
                       dtype=x.dtype, lod_from=x)
