"""Neural-net layers DSL.

Capability parity with reference python/paddle/fluid/layers/nn.py (fc :117,
embedding :229, dynamic_lstm :293, dynamic_gru :597, conv2d :1365,
pool2d :1838, batch_norm :2000, layer_norm :2151, dropout, softmax,
softmax_with_cross_entropy :4195, reshape :4382, topk, ...). Layers append
IR ops; the executor compiles the whole block into one XLA computation.
"""

from __future__ import annotations

from ..core import ir
from ..core.ir import seqlen_var_name
from ..layer_helper import LayerHelper
from .. import initializer as init


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None):
    """Fully-connected layer (reference nn.py:117)."""
    helper = LayerHelper("fc", **locals())
    dtype = input[0].dtype if isinstance(input, (list, tuple)) else input.dtype
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = param_attr if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * len(inputs)
    mul_results = []
    for inp, pattr in zip(inputs, param_attrs):
        in_features = 1
        for d in inp.shape[num_flatten_dims:]:
            in_features *= d
        w = helper.create_parameter(pattr, [in_features, size], dtype)
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op("mul", inputs={"X": [inp.name], "Y": [w.name]},
                         outputs={"Out": [tmp.name]},
                         attrs={"x_num_col_dims": num_flatten_dims,
                                "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op("sum", inputs={"X": [m.name for m in mul_results]},
                         outputs={"Out": [pre_bias.name]})
    pre_act = _append_bias(helper, pre_bias, dim_start=num_flatten_dims)
    pre_act.lod_level = inputs[0].lod_level
    return helper.append_activation(pre_act)


def _append_bias(helper, input_var, dim_start=1):
    battr = helper.bias_attr
    if battr is False:
        return input_var
    size = input_var.shape[-1] if input_var.shape else 1
    b = helper.create_parameter(battr, [size], input_var.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(dtype=input_var.dtype)
    helper.append_op("elementwise_add",
                     inputs={"X": [input_var.name], "Y": [b.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": -1})
    out.lod_level = input_var.lod_level
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """Embedding lookup (reference nn.py:229). is_sparse maps to the same
    dense-table gather on TPU (sparse grads become scatter-adds in XLA)."""
    helper = LayerHelper("embedding", **locals())
    w = helper.create_parameter(param_attr, size, dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("lookup_table",
                     inputs={"W": [w.name], "Ids": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"padding_idx": -1 if padding_idx is None else padding_idx,
                            "is_sparse": is_sparse,
                            "is_distributed": is_distributed})
    out.lod_level = input.lod_level
    return out


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=False, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """LSTM over a variable-length batch (reference nn.py:293). `input` is the
    x-projection [B, T, 4*size] (apply `fc` first, as in the reference)."""
    helper = LayerHelper("lstm", **locals())
    hidden_size = size // 4
    weight = helper.create_parameter(param_attr, [hidden_size, 4 * hidden_size], dtype)
    bias = helper.create_parameter(helper.bias_attr, [1, 4 * hidden_size], dtype,
                                   is_bias=True) if bias_attr is not False else None
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input.name], "Weight": [weight.name]}
    if bias is not None:
        inputs["Bias"] = [bias.name]
    if h_0 is not None:
        inputs["H0"] = [h_0.name]
    if c_0 is not None:
        inputs["C0"] = [c_0.name]
    seq = helper.ensure_seqlen_var(input)
    if seq is not None:
        inputs["SeqLen"] = [seq.name]
    helper.append_op("lstm", inputs=inputs,
                     outputs={"Hidden": [hidden.name], "Cell": [cell.name]},
                     attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation})
    hidden.lod_level = cell.lod_level = input.lod_level
    return hidden, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None, is_reverse=False,
                gate_activation="sigmoid", candidate_activation="tanh",
                h_0=None, name=None):
    """GRU over a variable-length batch (reference nn.py:597). `input` is the
    x-projection [B, T, 3*size]."""
    helper = LayerHelper("gru", **locals())
    dtype = input.dtype
    weight = helper.create_parameter(param_attr, [size, 3 * size], dtype)
    bias = helper.create_parameter(helper.bias_attr, [1, 3 * size], dtype,
                                   is_bias=True) if bias_attr is not False else None
    hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input.name], "Weight": [weight.name]}
    if bias is not None:
        inputs["Bias"] = [bias.name]
    if h_0 is not None:
        inputs["H0"] = [h_0.name]
    seq = helper.ensure_seqlen_var(input)
    if seq is not None:
        inputs["SeqLen"] = [seq.name]
    helper.append_op("gru", inputs=inputs, outputs={"Hidden": [hidden.name]},
                     attrs={"is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "activation": candidate_activation})
    hidden.lod_level = input.lod_level
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    helper = LayerHelper("gru_unit", **locals())
    dtype = input.dtype
    hidden_size = size // 3
    weight = helper.create_parameter(param_attr, [hidden_size, 3 * hidden_size], dtype)
    bias = helper.create_parameter(helper.bias_attr, [1, 3 * hidden_size], dtype,
                                   is_bias=True) if bias_attr is not False else None
    out_hidden = helper.create_variable_for_type_inference(dtype)
    reset_h = helper.create_variable_for_type_inference(dtype)
    gate = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input.name], "HiddenPrev": [hidden.name],
              "Weight": [weight.name]}
    if bias is not None:
        inputs["Bias"] = [bias.name]
    helper.append_op("gru_unit", inputs=inputs,
                     outputs={"Hidden": [out_hidden.name],
                              "ResetHiddenPrev": [reset_h.name],
                              "Gate": [gate.name]},
                     attrs={"activation": activation,
                            "gate_activation": gate_activation})
    return out_hidden, reset_h, gate


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    """2-D convolution, NCHW or NHWC (reference nn.py:1365). `use_cudnn` is
    accepted for API parity and ignored — XLA owns kernel selection on TPU.
    On TPU prefer data_format="NHWC": it matches the native conv layout and
    avoids relayout transposes. Filters are stored OIHW either way."""
    helper = LayerHelper("conv2d", **locals())
    dtype = input.dtype
    c_axis = 1 if data_format == "NCHW" else len(input.shape) - 1
    num_channels = input.shape[c_axis]
    fsize = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    filter_shape = [num_filters, num_channels // groups] + list(fsize)
    std = (2.0 / (fsize[0] * fsize[1] * num_channels)) ** 0.5
    w = helper.create_parameter(param_attr, filter_shape, dtype,
                                default_initializer=init.NormalInitializer(0.0, std))
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op("conv2d",
                     inputs={"Input": [input.name], "Filter": [w.name]},
                     outputs={"Output": [pre_bias.name]},
                     attrs={"strides": _pair(stride), "paddings": _pair(padding),
                            "dilations": _pair(dilation), "groups": groups,
                            "data_format": data_format})
    pre_act = _append_bias_channel(helper, pre_bias, axis=c_axis)
    return helper.append_activation(pre_act)


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


def _append_bias_channel(helper, input_var, axis=1):
    battr = helper.bias_attr
    if battr is False:
        return input_var
    size = input_var.shape[axis] if len(input_var.shape) > axis else 1
    b = helper.create_parameter(battr, [size], input_var.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(dtype=input_var.dtype)
    helper.append_op("elementwise_add",
                     inputs={"X": [input_var.name], "Y": [b.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    return out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, param_attr=None,
                     bias_attr=None, act=None, name=None, use_cudnn=True):
    helper = LayerHelper("conv2d_transpose", **locals())
    dtype = input.dtype
    num_channels = input.shape[1]
    fsize = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    filter_shape = [num_channels, num_filters] + list(fsize)
    w = helper.create_parameter(param_attr, filter_shape, dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op("conv2d_transpose",
                     inputs={"Input": [input.name], "Filter": [w.name]},
                     outputs={"Output": [pre_bias.name]},
                     attrs={"strides": _pair(stride), "paddings": _pair(padding),
                            "dilations": _pair(dilation)})
    pre_act = _append_bias_channel(helper, pre_bias)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False,
           exclusive=True, name=None, data_format="NCHW"):
    helper = LayerHelper("pool2d", **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("pool2d", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"pooling_type": pool_type, "ksize": _pair(pool_size),
                            "strides": _pair(pool_stride),
                            "paddings": _pair(pool_padding),
                            "global_pooling": global_pooling,
                            "exclusive": exclusive,
                            "data_format": data_format})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False):
    """Batch normalization (reference nn.py:2000)."""
    helper = LayerHelper("batch_norm", **locals())
    dtype = input.dtype
    c_axis = 1 if data_layout == "NCHW" else len(input.shape) - 1
    channels = input.shape[c_axis]
    scale = helper.create_parameter(param_attr, [channels], dtype,
                                    default_initializer=init.ConstantInitializer(1.0))
    bias = helper.create_parameter(helper.bias_attr, [channels], dtype, is_bias=True)
    mean = helper.create_parameter(
        moving_mean_name, [channels], dtype,
        default_initializer=init.ConstantInitializer(0.0), stop_gradient=True)
    variance = helper.create_parameter(
        moving_variance_name, [channels], dtype,
        default_initializer=init.ConstantInitializer(1.0), stop_gradient=True)
    mean.trainable = False
    variance.trainable = False
    y = helper.create_variable_for_type_inference(dtype)
    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op("batch_norm",
                     inputs={"X": [input.name], "Scale": [scale.name],
                             "Bias": [bias.name], "Mean": [mean.name],
                             "Variance": [variance.name]},
                     outputs={"Y": [y.name], "MeanOut": [mean.name],
                              "VarianceOut": [variance.name],
                              "SavedMean": [saved_mean.name],
                              "SavedVariance": [saved_var.name]},
                     attrs={"momentum": momentum, "epsilon": epsilon,
                            "is_test": is_test, "data_layout": data_layout})
    return helper.append_activation(y)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("layer_norm", **locals())
    dtype = input.dtype
    norm_shape = [1]
    for d in input.shape[begin_norm_axis:]:
        norm_shape[0] *= d
    inputs = {"X": [input.name]}
    if scale:
        s = helper.create_parameter(param_attr, norm_shape, dtype,
                                    default_initializer=init.ConstantInitializer(1.0))
        inputs["Scale"] = [s.name]
    if shift:
        b = helper.create_parameter(helper.bias_attr, norm_shape, dtype, is_bias=True)
        inputs["Bias"] = [b.name]
    y = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op("layer_norm", inputs=inputs,
                     outputs={"Y": [y.name], "Mean": [mean.name],
                              "Variance": [var.name]},
                     attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis})
    y.lod_level = input.lod_level
    return helper.append_activation(y)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    mask = helper.create_variable_for_type_inference(dtype=x.dtype, stop_gradient=True)
    helper.append_op("dropout", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "Mask": [mask.name]},
                     attrs={"dropout_prob": dropout_prob, "is_test": is_test,
                            "dropout_implementation": dropout_implementation})
    out.lod_level = x.lod_level
    return out


def softmax(input, axis=-1, use_cudnn=True, name=None):
    helper = LayerHelper("softmax", **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("softmax", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    out.lod_level = input.lod_level
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("cross_entropy",
                     inputs={"X": [input.name], "Label": [label.name]},
                     outputs={"Y": [out.name]},
                     attrs={"soft_label": soft_label, "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, return_softmax=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(dtype=logits.dtype)
    loss = helper.create_variable_for_type_inference(dtype=logits.dtype)
    helper.append_op("softmax_with_cross_entropy",
                     inputs={"Logits": [logits.name], "Label": [label.name]},
                     outputs={"Softmax": [softmax_out.name], "Loss": [loss.name]},
                     attrs={"soft_label": soft_label, "ignore_index": ignore_index})
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x.name], "Label": [label.name]},
                     outputs={"Out": [out.name]},
                     attrs={"ignore_index": ignore_index})
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("square_error_cost",
                     inputs={"X": [input.name], "Y": [label.name]},
                     outputs={"Out": [out.name]})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    diff = helper.create_variable_for_type_inference(dtype=x.dtype)
    inputs = {"X": [x.name], "Y": [y.name]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight.name]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight.name]
    helper.append_op("smooth_l1_loss", inputs=inputs,
                     outputs={"Out": [out.name], "Diff": [diff.name]},
                     attrs={"sigma": sigma or 1.0})
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("mean", inputs={"X": [x.name]}, outputs={"Out": [out.name]})
    return out


def _reduce_layer(op_type):
    def layer(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype=input.dtype)
        if dim is None:
            attrs = {"reduce_all": True, "keep_dim": keep_dim}
        else:
            dims = dim if isinstance(dim, (list, tuple)) else [dim]
            attrs = {"dim": list(dims), "keep_dim": keep_dim, "reduce_all": False}
        helper.append_op(op_type, inputs={"X": [input.name]},
                         outputs={"Out": [out.name]}, attrs=attrs)
        return out

    layer.__name__ = op_type
    return layer


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape", **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("reshape", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"shape": list(shape)})
    return helper.append_activation(out)


def squeeze(input, axes=None, name=None):
    helper = LayerHelper("squeeze", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("squeeze", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"axes": axes or []})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("unsqueeze", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"axes": list(axes)})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("transpose", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": list(perm)})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("matmul", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]},
                     attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y,
                            "alpha": float(alpha)})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(dtype=input.dtype)
    indices = helper.create_variable_for_type_inference(dtype="int64",
                                                        stop_gradient=True)
    helper.append_op("top_k", inputs={"X": [input.name]},
                     outputs={"Out": [values.name], "Indices": [indices.name]},
                     attrs={"k": k})
    return values, indices


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op("one_hot", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"depth": depth})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = 0
        sections = list(num_or_sections)
    n_outs = num if num else len(sections)
    outs = [helper.create_variable_for_type_inference(dtype=input.dtype)
            for _ in range(n_outs)]
    helper.append_op("split", inputs={"X": [input.name]},
                     outputs={"Out": [o.name for o in outs]},
                     attrs={"num": num, "sections": sections, "axis": dim})
    return outs


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("slice", inputs={"Input": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("gather", inputs={"X": [input.name], "Index": [index.name]},
                     outputs={"Out": [out.name]})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("scatter",
                     inputs={"X": [input.name], "Ids": [index.name],
                             "Updates": [updates.name]},
                     outputs={"Out": [out.name]}, attrs={"overwrite": overwrite})
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("expand", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"expand_times": list(expand_times)})
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    out = helper.create_variable_for_type_inference(dtype=x[0].dtype)
    helper.append_op("stack", inputs={"X": [v.name for v in x]},
                     outputs={"Y": [out.name]}, attrs={"axis": axis})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("pad", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
                     attrs={"paddings": list(paddings), "pad_value": pad_value})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    norm = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("l2_normalize", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "Norm": [norm.name]},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("clip", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
                     attrs={"min": float(min), "max": float(max)})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("clip_by_norm", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"max_norm": float(max_norm)})
    return out


def relu(x, name=None):
    from . import ops as _ops
    return _ops.relu(x, name=name)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("scale", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    out.lod_level = x.lod_level
    return helper.append_activation(out)


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", **locals())
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = [int(d) if d > 0 else 1 for d in x.shape[1:]]
    alpha = helper.create_parameter(param_attr, alpha_shape, x.dtype,
                                    default_initializer=init.ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("prelu", inputs={"X": [x.name], "Alpha": [alpha.name]},
                     outputs={"Out": [out.name]}, attrs={"mode": mode})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    mid = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("lrn", inputs={"X": [input.name]},
                     outputs={"Out": [out.name], "MidOut": [mid.name]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


# -- sequence layers (LoD analogs) ------------------------------------------

def _seq_inputs(helper, x, extra=None):
    inputs = {"X": [x.name]}
    seq = helper.ensure_seqlen_var(x)
    if seq is not None:
        inputs["SeqLen"] = [seq.name]
    if extra:
        inputs.update(extra)
    return inputs


def _alias_seqlen(helper, src, dst):
    """Length-preserving sequence ops (sequence_conv, row_conv, ...) carry
    their input's @SEQLEN onto the output with an explicit assign — the
    runtime propagation in lowering.py only walks propagate_seqlen=True ops,
    and a downstream sequence op would otherwise read an unmaterialized
    companion."""
    seq_src = helper.ensure_seqlen_var(src)
    if seq_src is None:
        return
    dst.lod_level = max(dst.lod_level, src.lod_level)
    seq_dst = helper.ensure_seqlen_var(dst)
    helper.append_op("assign", inputs={"X": [seq_src.name]},
                     outputs={"Out": [seq_dst.name]})


def sequence_pool(input, pool_type, is_test=False):
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("sequence_pool", inputs=_seq_inputs(helper, input),
                     outputs={"Out": [out.name]},
                     attrs={"pooltype": pool_type.upper()})
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def cos_sim(X, Y):
    """Row-wise cosine similarity (reference nn.py cos_sim)."""
    helper = LayerHelper("cos_sim")
    out = helper.create_variable_for_type_inference(dtype=X.dtype)
    xn = helper.create_variable_for_type_inference(dtype=X.dtype)
    yn = helper.create_variable_for_type_inference(dtype=X.dtype)
    helper.append_op("cos_sim", inputs={"X": [X.name], "Y": [Y.name]},
                     outputs={"Out": [out.name], "XNorm": [xn.name],
                              "YNorm": [yn.name]})
    return out


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("sequence_softmax", inputs=_seq_inputs(helper, input),
                     outputs={"Out": [out.name]})
    out.lod_level = input.lod_level
    _alias_seqlen(helper, input, out)
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("sequence_expand",
                     inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]}, attrs={"ref_level": ref_level})
    out.lod_level = y.lod_level
    # the output inherits Y's time axis, so its lengths are Y's
    _alias_seqlen(helper, y, out)
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None):
    helper = LayerHelper("sequence_conv", **locals())
    dtype = input.dtype
    d = input.shape[-1]
    w = helper.create_parameter(param_attr, [filter_size * d, num_filters], dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("sequence_conv",
                     inputs=_seq_inputs(helper, input, {"Filter": [w.name]}),
                     outputs={"Out": [out.name]},
                     attrs={"contextLength": filter_size,
                            "contextStart": -(filter_size // 2),
                            "contextStride": filter_stride})
    out.lod_level = input.lod_level
    pre_act = _append_bias(helper, out)
    final = helper.append_activation(pre_act)
    # alias onto the FINAL var: downstream sequence ops read its companion,
    # and pruning keeps the alias only if its output is the one they read
    _alias_seqlen(helper, input, final)
    return final


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    out.lod_level = input.lod_level
    outputs = {"Out": [out.name]}
    if input.lod_level > 0:
        # lengths scale by D/new_dim — emitted by the op itself (OutLen)
        seq_out = helper.ensure_seqlen_var(out)
        outputs["OutLen"] = [seq_out.name]
    helper.append_op("sequence_reshape", inputs=_seq_inputs(helper, input),
                     outputs=outputs, attrs={"new_dim": new_dim})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", **locals())
    d = input.shape[-1]
    w = helper.create_parameter(param_attr, [future_context_size + 1, d],
                                input.dtype)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("row_conv",
                     inputs=_seq_inputs(helper, input, {"Filter": [w.name]}),
                     outputs={"Out": [out.name]})
    out.lod_level = input.lod_level
    final = helper.append_activation(out)
    _alias_seqlen(helper, input, final)
    return final


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    p = _pair(padding)
    helper.append_op("im2sequence", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"kernels": _pair(filter_size), "strides": _pair(stride),
                            "paddings": p + p})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op("sequence_mask", inputs={"X": [x.name]},
                     outputs={"Y": [out.name]},
                     attrs={"maxlen": maxlen if maxlen else -1, "out_dtype": dtype})
    return out
