"""Neural-net layers DSL.

Capability parity with reference python/paddle/fluid/layers/nn.py (fc :117,
embedding :229, dynamic_lstm :293, dynamic_gru :597, conv2d :1365,
pool2d :1838, batch_norm :2000, layer_norm :2151, dropout, softmax,
softmax_with_cross_entropy :4195, reshape :4382, topk, ...). Layers append
IR ops; the executor compiles the whole block into one XLA computation.
"""

from __future__ import annotations

from ..core import ir
from ..core import registry as _registry
from ..core.ir import seqlen_var_name
from ..layer_helper import LayerHelper
from .. import initializer as init


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, is_test=False, name=None):
    """Fully-connected layer (reference nn.py:117)."""
    helper = LayerHelper("fc", **locals())
    dtype = input[0].dtype if isinstance(input, (list, tuple)) else input.dtype
    inputs = input if isinstance(input, (list, tuple)) else [input]
    param_attrs = param_attr if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * len(inputs)
    mul_results = []
    for inp, pattr in zip(inputs, param_attrs):
        in_features = 1
        for d in inp.shape[num_flatten_dims:]:
            in_features *= d
        w = helper.create_parameter(pattr, [in_features, size], dtype)
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op("mul", inputs={"X": [inp.name], "Y": [w.name]},
                         outputs={"Out": [tmp.name]},
                         attrs={"x_num_col_dims": num_flatten_dims,
                                "y_num_col_dims": 1})
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(dtype)
        helper.append_op("sum", inputs={"X": [m.name for m in mul_results]},
                         outputs={"Out": [pre_bias.name]})
    pre_act = _append_bias(helper, pre_bias, dim_start=num_flatten_dims)
    pre_act.lod_level = inputs[0].lod_level
    return helper.append_activation(pre_act)


def _append_bias(helper, input_var, dim_start=1):
    battr = helper.bias_attr
    if battr is False:
        return input_var
    size = input_var.shape[-1] if input_var.shape else 1
    b = helper.create_parameter(battr, [size], input_var.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(dtype=input_var.dtype)
    helper.append_op("elementwise_add",
                     inputs={"X": [input_var.name], "Y": [b.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": -1})
    out.lod_level = input_var.lod_level
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """Embedding lookup (reference nn.py:229). is_sparse maps to the same
    dense-table gather on TPU (sparse grads become scatter-adds in XLA)."""
    helper = LayerHelper("embedding", **locals())
    w = helper.create_parameter(param_attr, size, dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("lookup_table",
                     inputs={"W": [w.name], "Ids": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"padding_idx": -1 if padding_idx is None else padding_idx,
                            "is_sparse": is_sparse,
                            "is_distributed": is_distributed})
    out.lod_level = input.lod_level
    return out


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", dtype="float32", name=None):
    """LSTM over a variable-length batch (reference nn.py:293, including
    its use_peepholes=True default). `input` is the x-projection
    [B, T, 4*size] (apply `fc` first, as in the reference). With peepholes
    the bias packs [4H gate biases | W_ic | W_if | W_oc] (lstm_op.cc)."""
    helper = LayerHelper("lstm", **locals())
    hidden_size = size // 4
    bias_cols = 7 * hidden_size if use_peepholes else 4 * hidden_size
    weight = helper.create_parameter(param_attr, [hidden_size, 4 * hidden_size], dtype)
    bias = helper.create_parameter(helper.bias_attr, [1, bias_cols], dtype,
                                   is_bias=True) if bias_attr is not False else None
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input.name], "Weight": [weight.name]}
    if bias is not None:
        inputs["Bias"] = [bias.name]
    if h_0 is not None:
        inputs["H0"] = [h_0.name]
    if c_0 is not None:
        inputs["C0"] = [c_0.name]
    seq = helper.ensure_seqlen_var(input)
    if seq is not None:
        inputs["SeqLen"] = [seq.name]
    helper.append_op("lstm", inputs=inputs,
                     outputs={"Hidden": [hidden.name], "Cell": [cell.name]},
                     attrs={"use_peepholes": use_peepholes, "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation})
    hidden.lod_level = cell.lod_level = input.lod_level
    return hidden, cell


def dynamic_gru(input, size, param_attr=None, bias_attr=None, is_reverse=False,
                gate_activation="sigmoid", candidate_activation="tanh",
                h_0=None, name=None):
    """GRU over a variable-length batch (reference nn.py:597). `input` is the
    x-projection [B, T, 3*size]."""
    helper = LayerHelper("gru", **locals())
    dtype = input.dtype
    weight = helper.create_parameter(param_attr, [size, 3 * size], dtype)
    bias = helper.create_parameter(helper.bias_attr, [1, 3 * size], dtype,
                                   is_bias=True) if bias_attr is not False else None
    hidden = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input.name], "Weight": [weight.name]}
    if bias is not None:
        inputs["Bias"] = [bias.name]
    if h_0 is not None:
        inputs["H0"] = [h_0.name]
    seq = helper.ensure_seqlen_var(input)
    if seq is not None:
        inputs["SeqLen"] = [seq.name]
    helper.append_op("gru", inputs=inputs, outputs={"Hidden": [hidden.name]},
                     attrs={"is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "activation": candidate_activation})
    hidden.lod_level = input.lod_level
    return hidden


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid"):
    helper = LayerHelper("gru_unit", **locals())
    dtype = input.dtype
    hidden_size = size // 3
    weight = helper.create_parameter(param_attr, [hidden_size, 3 * hidden_size], dtype)
    bias = helper.create_parameter(helper.bias_attr, [1, 3 * hidden_size], dtype,
                                   is_bias=True) if bias_attr is not False else None
    out_hidden = helper.create_variable_for_type_inference(dtype)
    reset_h = helper.create_variable_for_type_inference(dtype)
    gate = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input.name], "HiddenPrev": [hidden.name],
              "Weight": [weight.name]}
    if bias is not None:
        inputs["Bias"] = [bias.name]
    helper.append_op("gru_unit", inputs=inputs,
                     outputs={"Hidden": [out_hidden.name],
                              "ResetHiddenPrev": [reset_h.name],
                              "Gate": [gate.name]},
                     attrs={"activation": activation,
                            "gate_activation": gate_activation})
    return out_hidden, reset_h, gate


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    """2-D convolution, NCHW or NHWC (reference nn.py:1365). `use_cudnn` is
    accepted for API parity and ignored — XLA owns kernel selection on TPU.
    On TPU prefer data_format="NHWC": it matches the native conv layout and
    avoids relayout transposes. Filters are stored OIHW either way."""
    helper = LayerHelper("conv2d", **locals())
    dtype = input.dtype
    c_axis = 1 if data_format == "NCHW" else len(input.shape) - 1
    num_channels = input.shape[c_axis]
    fsize = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    filter_shape = [num_filters, num_channels // groups] + list(fsize)
    std = (2.0 / (fsize[0] * fsize[1] * num_channels)) ** 0.5
    w = helper.create_parameter(param_attr, filter_shape, dtype,
                                default_initializer=init.NormalInitializer(0.0, std))
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op("conv2d",
                     inputs={"Input": [input.name], "Filter": [w.name]},
                     outputs={"Output": [pre_bias.name]},
                     attrs={"strides": _pair(stride), "paddings": _pair(padding),
                            "dilations": _pair(dilation), "groups": groups,
                            "data_format": data_format})
    pre_act = _append_bias_channel(helper, pre_bias, axis=c_axis)
    return helper.append_activation(pre_act)


def _pair(v):
    return list(v) if isinstance(v, (list, tuple)) else [v, v]


def _append_bias_channel(helper, input_var, axis=1):
    battr = helper.bias_attr
    if battr is False:
        return input_var
    size = input_var.shape[axis] if len(input_var.shape) > axis else 1
    b = helper.create_parameter(battr, [size], input_var.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(dtype=input_var.dtype)
    helper.append_op("elementwise_add",
                     inputs={"X": [input_var.name], "Y": [b.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    return out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, param_attr=None,
                     bias_attr=None, act=None, name=None, use_cudnn=True):
    helper = LayerHelper("conv2d_transpose", **locals())
    dtype = input.dtype
    num_channels = input.shape[1]
    if filter_size is None:
        # derive from output_size (reference nn.py:2377-2390)
        if output_size is None:
            raise ValueError("filter_size or output_size must be set")
        osz = [output_size] * 2 if isinstance(output_size, int) \
            else list(output_size)
        st, pd, dl = _pair(stride), _pair(padding), _pair(dilation)
        filter_size = [(osz[i] - (input.shape[2 + i] - 1) * st[i]
                        + 2 * pd[i] - 1) // dl[i] + 1 for i in range(2)]
    fsize = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    filter_shape = [num_channels, num_filters] + list(fsize)
    w = helper.create_parameter(param_attr, filter_shape, dtype)
    pre_bias = helper.create_variable_for_type_inference(dtype)
    helper.append_op("conv2d_transpose",
                     inputs={"Input": [input.name], "Filter": [w.name]},
                     outputs={"Output": [pre_bias.name]},
                     attrs={"strides": _pair(stride), "paddings": _pair(padding),
                            "dilations": _pair(dilation)})
    pre_act = _append_bias_channel(helper, pre_bias)
    return helper.append_activation(pre_act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1, pool_padding=0,
           global_pooling=False, use_cudnn=True, ceil_mode=False,
           exclusive=True, name=None, data_format="NCHW", adaptive=False):
    helper = LayerHelper("pool2d", **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("pool2d", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"pooling_type": pool_type, "ksize": _pair(pool_size),
                            "strides": _pair(pool_stride),
                            "paddings": _pair(pool_padding),
                            "global_pooling": global_pooling,
                            "exclusive": exclusive, "adaptive": adaptive,
                            "data_format": data_format})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=False):
    """Batch normalization (reference nn.py:2000)."""
    helper = LayerHelper("batch_norm", **locals())
    dtype = input.dtype
    c_axis = 1 if data_layout == "NCHW" else len(input.shape) - 1
    channels = input.shape[c_axis]
    scale = helper.create_parameter(param_attr, [channels], dtype,
                                    default_initializer=init.ConstantInitializer(1.0))
    bias = helper.create_parameter(helper.bias_attr, [channels], dtype, is_bias=True)
    mean = helper.create_parameter(
        moving_mean_name, [channels], dtype,
        default_initializer=init.ConstantInitializer(0.0), stop_gradient=True)
    variance = helper.create_parameter(
        moving_variance_name, [channels], dtype,
        default_initializer=init.ConstantInitializer(1.0), stop_gradient=True)
    mean.trainable = False
    variance.trainable = False
    y = helper.create_variable_for_type_inference(dtype)
    saved_mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op("batch_norm",
                     inputs={"X": [input.name], "Scale": [scale.name],
                             "Bias": [bias.name], "Mean": [mean.name],
                             "Variance": [variance.name]},
                     outputs={"Y": [y.name], "MeanOut": [mean.name],
                              "VarianceOut": [variance.name],
                              "SavedMean": [saved_mean.name],
                              "SavedVariance": [saved_var.name]},
                     attrs={"momentum": momentum, "epsilon": epsilon,
                            "is_test": is_test, "data_layout": data_layout})
    return helper.append_activation(y)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("layer_norm", **locals())
    dtype = input.dtype
    norm_shape = [1]
    for d in input.shape[begin_norm_axis:]:
        norm_shape[0] *= d
    inputs = {"X": [input.name]}
    if scale:
        s = helper.create_parameter(param_attr, norm_shape, dtype,
                                    default_initializer=init.ConstantInitializer(1.0))
        inputs["Scale"] = [s.name]
    if shift:
        b = helper.create_parameter(helper.bias_attr, norm_shape, dtype, is_bias=True)
        inputs["Bias"] = [b.name]
    y = helper.create_variable_for_type_inference(dtype)
    mean = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype, stop_gradient=True)
    helper.append_op("layer_norm", inputs=inputs,
                     outputs={"Y": [y.name], "Mean": [mean.name],
                              "Variance": [var.name]},
                     attrs={"epsilon": epsilon, "begin_norm_axis": begin_norm_axis})
    y.lod_level = input.lod_level
    return helper.append_activation(y)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    mask = helper.create_variable_for_type_inference(dtype=x.dtype, stop_gradient=True)
    helper.append_op("dropout", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "Mask": [mask.name]},
                     attrs={"dropout_prob": dropout_prob, "is_test": is_test,
                            "dropout_implementation": dropout_implementation})
    out.lod_level = x.lod_level
    return out


def softmax(input, axis=-1, use_cudnn=True, name=None):
    helper = LayerHelper("softmax", **locals())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("softmax", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": axis})
    out.lod_level = input.lod_level
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    helper = LayerHelper("cross_entropy")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("cross_entropy",
                     inputs={"X": [input.name], "Label": [label.name]},
                     outputs={"Y": [out.name]},
                     attrs={"soft_label": soft_label, "ignore_index": ignore_index})
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, return_softmax=False):
    helper = LayerHelper("softmax_with_cross_entropy")
    softmax_out = helper.create_variable_for_type_inference(dtype=logits.dtype)
    loss = helper.create_variable_for_type_inference(dtype=logits.dtype)
    # hidden log-sum-exp output ([rows, 1] f32 — tiny): the grad rule
    # rebuilds softmax as exp(logits - lse) from it, pure elementwise, so
    # the backward re-runs no [rows, V] reductions and no [rows, V]
    # probabilities tensor crosses the fwd/bwd boundary
    lse_out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op("softmax_with_cross_entropy",
                     inputs={"Logits": [logits.name], "Label": [label.name]},
                     outputs={"Softmax": [softmax_out.name], "Loss": [loss.name],
                              "LSE": [lse_out.name]},
                     attrs={"soft_label": soft_label, "ignore_index": ignore_index})
    lse_out.stop_gradient = True
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("sigmoid_cross_entropy_with_logits",
                     inputs={"X": [x.name], "Label": [label.name]},
                     outputs={"Out": [out.name]},
                     attrs={"ignore_index": ignore_index})
    return out


def square_error_cost(input, label):
    helper = LayerHelper("square_error_cost")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("square_error_cost",
                     inputs={"X": [input.name], "Y": [label.name]},
                     outputs={"Out": [out.name]})
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    helper = LayerHelper("smooth_l1_loss")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    diff = helper.create_variable_for_type_inference(dtype=x.dtype)
    inputs = {"X": [x.name], "Y": [y.name]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight.name]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight.name]
    helper.append_op("smooth_l1_loss", inputs=inputs,
                     outputs={"Out": [out.name], "Diff": [diff.name]},
                     attrs={"sigma": sigma or 1.0})
    return out


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("mean", inputs={"X": [x.name]}, outputs={"Out": [out.name]})
    return out


def _reduce_layer(op_type):
    def layer(input, dim=None, keep_dim=False, name=None):
        helper = LayerHelper(op_type, name=name)
        out = helper.create_variable_for_type_inference(dtype=input.dtype)
        if dim is None:
            attrs = {"reduce_all": True, "keep_dim": keep_dim}
        else:
            dims = dim if isinstance(dim, (list, tuple)) else [dim]
            attrs = {"dim": list(dims), "keep_dim": keep_dim, "reduce_all": False}
        helper.append_op(op_type, inputs={"X": [input.name]},
                         outputs={"Out": [out.name]}, attrs=attrs)
        return out

    layer.__name__ = op_type
    return layer


reduce_sum = _reduce_layer("reduce_sum")
reduce_mean = _reduce_layer("reduce_mean")
reduce_max = _reduce_layer("reduce_max")
reduce_min = _reduce_layer("reduce_min")
reduce_prod = _reduce_layer("reduce_prod")


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape", **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("reshape", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"shape": list(shape)})
    return helper.append_activation(out)


def squeeze(input, axes=None, name=None):
    helper = LayerHelper("squeeze", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("squeeze", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"axes": axes or []})
    return out


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("unsqueeze", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"axes": list(axes)})
    return out


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("transpose", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"axis": list(perm)})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("matmul", inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]},
                     attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y,
                            "alpha": float(alpha)})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(dtype=input.dtype)
    indices = helper.create_variable_for_type_inference(dtype="int64",
                                                        stop_gradient=True)
    helper.append_op("top_k", inputs={"X": [input.name]},
                     outputs={"Out": [values.name], "Indices": [indices.name]},
                     attrs={"k": k})
    return values, indices


def one_hot(input, depth):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op("one_hot", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs={"depth": depth})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    if isinstance(num_or_sections, int):
        num = num_or_sections
        sections = []
    else:
        num = 0
        sections = list(num_or_sections)
    n_outs = num if num else len(sections)
    outs = [helper.create_variable_for_type_inference(dtype=input.dtype)
            for _ in range(n_outs)]
    helper.append_op("split", inputs={"X": [input.name]},
                     outputs={"Out": [o.name for o in outs]},
                     attrs={"num": num, "sections": sections, "axis": dim})
    return outs


def slice(input, axes, starts, ends):
    helper = LayerHelper("slice")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("slice", inputs={"Input": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


def gather(input, index):
    helper = LayerHelper("gather")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("gather", inputs={"X": [input.name], "Index": [index.name]},
                     outputs={"Out": [out.name]})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("scatter",
                     inputs={"X": [input.name], "Ids": [index.name],
                             "Updates": [updates.name]},
                     outputs={"Out": [out.name]}, attrs={"overwrite": overwrite})
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("expand", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"expand_times": list(expand_times)})
    return out


def stack(x, axis=0):
    helper = LayerHelper("stack")
    out = helper.create_variable_for_type_inference(dtype=x[0].dtype)
    helper.append_op("stack", inputs={"X": [v.name for v in x]},
                     outputs={"Y": [out.name]}, attrs={"axis": axis})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    helper = LayerHelper("pad", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("pad", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
                     attrs={"paddings": list(paddings), "pad_value": pad_value})
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    norm = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("l2_normalize", inputs={"X": [x.name]},
                     outputs={"Out": [out.name], "Norm": [norm.name]},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def clip(x, min, max, name=None):
    helper = LayerHelper("clip", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("clip", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
                     attrs={"min": float(min), "max": float(max)})
    return out


def clip_by_norm(x, max_norm, name=None):
    helper = LayerHelper("clip_by_norm", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("clip_by_norm", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]}, attrs={"max_norm": float(max_norm)})
    return out


def relu(x, name=None):
    from . import ops as _ops
    return _ops.relu(x, name=name)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", **locals())
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("scale", inputs={"X": [x.name]}, outputs={"Out": [out.name]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    out.lod_level = x.lod_level
    return helper.append_activation(out)


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", **locals())
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = [int(d) if d > 0 else 1 for d in x.shape[1:]]
    alpha = helper.create_parameter(param_attr, alpha_shape, x.dtype,
                                    default_initializer=init.ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("prelu", inputs={"X": [x.name], "Alpha": [alpha.name]},
                     outputs={"Out": [out.name]}, attrs={"mode": mode})
    return out


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    mid = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("lrn", inputs={"X": [input.name]},
                     outputs={"Out": [out.name], "MidOut": [mid.name]},
                     attrs={"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


# -- sequence layers (LoD analogs) ------------------------------------------

# layers whose op rules implement the innermost-level (nested LoD)
# adapter — everything else still refuses level-2 input at build time
# rather than failing cryptically inside jit tracing
_NESTED_CAPABLE = {"sequence_pool", "sequence_softmax", "sequence_conv",
                   "sequence_reshape", "sequence_erase", "sequence_slice",
                   "sequence_expand", "sequence_concat"}


def _seq_inputs(helper, x, extra=None):
    # sequence ops act on the INNERMOST LoD level (reference
    # lod_tensor.h:110): for nested (level-2) inputs the wired companion
    # is the [B, S] inner lengths; the op rules flatten (doc, sentence)
    # rows, run the level-1 semantics, and restore the nesting
    if (getattr(x, "lod_level", 0) >= 2
            and helper.layer_type not in _NESTED_CAPABLE):
        raise NotImplementedError(
            f"{helper.layer_type}: nested (level-2) LoD input is supported "
            f"by {sorted(_NESTED_CAPABLE)}; pool the inner level first")
    inputs = {"X": [x.name]}
    level = max(getattr(x, "lod_level", 0) - 1, 0)
    seq = helper.ensure_seqlen_var(x, level=level)
    if seq is not None:
        inputs["SeqLen"] = [seq.name]
    if extra:
        inputs.update(extra)
    return inputs


def _alias_seqlen(helper, src, dst):
    """Length-preserving sequence ops (sequence_conv, row_conv, ...) carry
    their input's @SEQLEN onto the output with an explicit assign — the
    runtime propagation in lowering.py only walks propagate_seqlen=True ops,
    and a downstream sequence op would otherwise read an unmaterialized
    companion. All LoD levels are aliased (outer doc counts AND inner
    sentence lengths for nested inputs)."""
    dst.lod_level = max(dst.lod_level, src.lod_level)
    for level in range(dst.lod_level):
        seq_src = helper.ensure_seqlen_var(src, level=level)
        if seq_src is None:
            continue
        seq_dst = helper.ensure_seqlen_var(dst, level=level)
        helper.append_op("assign", inputs={"X": [seq_src.name]},
                         outputs={"Out": [seq_dst.name]})


def sequence_pool(input, pool_type, is_test=False):
    helper = LayerHelper("sequence_pool")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    if input.lod_level >= 2:
        # nested LoD: pool the INNERMOST level (reference semantics); the
        # result keeps the remaining outer level, whose lengths alias the
        # input's outer companion
        inner = helper.ensure_seqlen_var(input, level=1)
        helper.append_op("sequence_pool",
                         inputs={"X": [input.name],
                                 "SeqLen": [inner.name]},
                         outputs={"Out": [out.name]},
                         attrs={"pooltype": pool_type.upper()})
        out.lod_level = input.lod_level - 1
        outer_src = helper.ensure_seqlen_var(input, level=0)
        outer_dst = helper.ensure_seqlen_var(out, level=0)
        helper.append_op("assign", inputs={"X": [outer_src.name]},
                         outputs={"Out": [outer_dst.name]})
        return out
    helper.append_op("sequence_pool", inputs=_seq_inputs(helper, input),
                     outputs={"Out": [out.name]},
                     attrs={"pooltype": pool_type.upper()})
    return out


def sequence_first_step(input):
    return sequence_pool(input, "first")


def sequence_last_step(input):
    return sequence_pool(input, "last")


def cos_sim(X, Y):
    """Row-wise cosine similarity (reference nn.py cos_sim)."""
    helper = LayerHelper("cos_sim")
    out = helper.create_variable_for_type_inference(dtype=X.dtype)
    xn = helper.create_variable_for_type_inference(dtype=X.dtype)
    yn = helper.create_variable_for_type_inference(dtype=X.dtype)
    helper.append_op("cos_sim", inputs={"X": [X.name], "Y": [Y.name]},
                     outputs={"Out": [out.name], "XNorm": [xn.name],
                              "YNorm": [yn.name]})
    return out


def sequence_softmax(input, use_cudnn=False, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("sequence_softmax", inputs=_seq_inputs(helper, input),
                     outputs={"Out": [out.name]})
    out.lod_level = input.lod_level
    _alias_seqlen(helper, input, out)
    return out


def sequence_concat(input, name=None):
    """Concatenate sequences row-wise along the time axis (reference
    sequence_concat_op.cc): row b of the output is
    concat_i(x_i[b, :len_i[b]]), left-aligned, with length sum_i len_i.
    Inputs without a lengths companion contribute their full rows.
    Nested (level-2) inputs concatenate the innermost level per
    (doc, sentence) row; the outer counts ride through from the first
    input."""
    helper = LayerHelper("sequence_concat", name=name)
    xs = list(input) if isinstance(input, (list, tuple)) else [input]
    levels = {getattr(x, "lod_level", 0) for x in xs}
    if len(levels) > 1:
        # refuse at build time (the module contract above _NESTED_CAPABLE):
        # the nested op rule flattens every input as [B, S, ...], so a
        # mixed-level list would die cryptically inside jit tracing
        raise ValueError(
            f"sequence_concat: inputs must share one LoD level, got "
            f"{sorted(levels)} (reference sequence_concat_op.cc requires "
            f"matching LoD structure)")
    out = helper.create_variable_for_type_inference(dtype=xs[0].dtype)
    out.lod_level = max(levels)
    inputs = {"X": [x.name for x in xs]}
    seq_names, wired = [], False
    for x in xs:
        level = max(getattr(x, "lod_level", 0) - 1, 0)
        s = helper.ensure_seqlen_var(x, level=level)
        if s is None:
            seq_names.append(_registry.EMPTY_VAR)   # full-length rows
        else:
            seq_names.append(s.name)
            wired = True
    outputs = {"Out": [out.name]}
    if wired and out.lod_level:
        inputs["SeqLen"] = seq_names
        seq_out = helper.ensure_seqlen_var(out, level=out.lod_level - 1)
        outputs["OutLen"] = [seq_out.name]
        for lvl in range(out.lod_level - 1):      # nested: outer doc counts
            src = helper.ensure_seqlen_var(xs[0], level=lvl)
            if src is not None:
                dst = helper.ensure_seqlen_var(out, level=lvl)
                helper.append_op("assign", inputs={"X": [src.name]},
                                 outputs={"Out": [dst.name]})
    helper.append_op("sequence_concat", inputs=inputs, outputs=outputs)
    return out


def sequence_expand(x, y, ref_level=-1, name=None):
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("sequence_expand",
                     inputs={"X": [x.name], "Y": [y.name]},
                     outputs={"Out": [out.name]}, attrs={"ref_level": ref_level})
    out.lod_level = y.lod_level
    # the output inherits Y's time axis, so its lengths are Y's
    _alias_seqlen(helper, y, out)
    return out


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=None, bias_attr=None, param_attr=None, act=None):
    helper = LayerHelper("sequence_conv", **locals())
    dtype = input.dtype
    d = input.shape[-1]
    w = helper.create_parameter(param_attr, [filter_size * d, num_filters], dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("sequence_conv",
                     inputs=_seq_inputs(helper, input, {"Filter": [w.name]}),
                     outputs={"Out": [out.name]},
                     attrs={"contextLength": filter_size,
                            "contextStart": -(filter_size // 2),
                            "contextStride": filter_stride})
    out.lod_level = input.lod_level
    pre_act = _append_bias(helper, out)
    final = helper.append_activation(pre_act)
    # alias onto the FINAL var: downstream sequence ops read its companion,
    # and pruning keeps the alias only if its output is the one they read
    _alias_seqlen(helper, input, final)
    return final


def sequence_reshape(input, new_dim):
    helper = LayerHelper("sequence_reshape")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    out.lod_level = input.lod_level
    outputs = {"Out": [out.name]}
    if input.lod_level > 0:
        # lengths scale by D/new_dim — emitted by the op itself (OutLen)
        # onto the INNERMOST companion; outer doc counts ride through
        seq_out = helper.ensure_seqlen_var(out, level=input.lod_level - 1)
        outputs["OutLen"] = [seq_out.name]
    helper.append_op("sequence_reshape", inputs=_seq_inputs(helper, input),
                     outputs=outputs, attrs={"new_dim": new_dim})
    for level in range(input.lod_level - 1):
        src = helper.ensure_seqlen_var(input, level=level)
        if src is not None:
            dst = helper.ensure_seqlen_var(out, level=level)
            helper.append_op("assign", inputs={"X": [src.name]},
                             outputs={"Out": [dst.name]})
    return out


def row_conv(input, future_context_size, param_attr=None, act=None):
    helper = LayerHelper("row_conv", **locals())
    d = input.shape[-1]
    w = helper.create_parameter(param_attr, [future_context_size + 1, d],
                                input.dtype)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("row_conv",
                     inputs=_seq_inputs(helper, input, {"Filter": [w.name]}),
                     outputs={"Out": [out.name]})
    out.lod_level = input.lod_level
    final = helper.append_activation(out)
    _alias_seqlen(helper, input, final)
    return final


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    helper = LayerHelper("im2sequence", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    p = _pair(padding)
    helper.append_op("im2sequence", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"kernels": _pair(filter_size), "strides": _pair(stride),
                            "paddings": p + p})
    return out


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op("sequence_mask", inputs={"X": [x.name]},
                     outputs={"Y": [out.name]},
                     attrs={"maxlen": maxlen if maxlen else -1, "out_dtype": dtype})
    return out


# ---------------------------------------------------------------------------
# breadth layers completing the reference nn.py surface (3-D, image, misc)
# ---------------------------------------------------------------------------

def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, act=None, name=None):
    """reference nn.py conv3d."""
    helper = LayerHelper("conv3d", **locals())
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 3
    in_c = input.shape[1]
    g = groups or 1
    w = helper.create_parameter(param_attr,
                                [num_filters, in_c // g] + list(k),
                                input.dtype,
                                default_initializer=init.MSRAInitializer())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    inputs = {"Input": [input.name], "Filter": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, [num_filters],
                                    input.dtype, is_bias=True)
        inputs["Bias"] = [b.name]
    helper.append_op("conv3d", inputs=inputs,
                     outputs={"Output": [out.name]},
                     attrs={"strides": list(_triple3(stride)),
                            "paddings": list(_triple3(padding)),
                            "dilations": list(_triple3(dilation)),
                            "groups": g})
    return helper.append_activation(out)


def _triple3(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * 3


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, param_attr=None,
                     bias_attr=None, act=None, name=None):
    """reference nn.py conv3d_transpose."""
    helper = LayerHelper("conv3d_transpose", **locals())
    stride3 = _triple3(stride)
    pad3 = _triple3(padding)
    dil3 = _triple3(dilation)
    if filter_size is None:
        # reference conv2d_transpose:2377 derives the kernel from the
        # requested output: k = (out - (in-1)*s + 2p - 1)/d + 1
        if output_size is None:
            raise ValueError("filter_size or output_size must be set")
        osz = [output_size] * 3 if isinstance(output_size, int) \
            else list(output_size)
        k = [(osz[i] - (input.shape[2 + i] - 1) * stride3[i]
              + 2 * pad3[i] - 1) // dil3[i] + 1 for i in range(3)]
    else:
        k = filter_size if isinstance(filter_size, (list, tuple)) \
            else [filter_size] * 3
    in_c = input.shape[1]
    w = helper.create_parameter(param_attr, [in_c, num_filters] + list(k),
                                input.dtype,
                                default_initializer=init.XavierInitializer())
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    inputs = {"Input": [input.name], "Filter": [w.name]}
    if bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, [num_filters],
                                    input.dtype, is_bias=True)
        inputs["Bias"] = [b.name]
    helper.append_op("conv3d_transpose", inputs=inputs,
                     outputs={"Output": [out.name]},
                     attrs={"strides": list(_triple3(stride)),
                            "paddings": list(_triple3(padding)),
                            "dilations": list(_triple3(dilation))})
    return helper.append_activation(out)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, name=None):
    helper = LayerHelper("pool3d", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("pool3d", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]},
                     attrs={"pooling_type": pool_type,
                            "ksize": list(_triple3(pool_size)),
                            "strides": list(_triple3(pool_stride)),
                            "paddings": list(_triple3(pool_padding)),
                            "global_pooling": global_pooling})
    return out


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR"):
    """reference nn.py image_resize (BILINEAR/NEAREST)."""
    helper = LayerHelper("image_resize", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    attrs = {"interp_method": resample.lower()}
    if out_shape is not None:
        attrs["out_h"], attrs["out_w"] = int(out_shape[0]), int(out_shape[1])
    if scale is not None:
        attrs["scale"] = float(scale)
    helper.append_op("bilinear_interp", inputs={"X": [input.name]},
                     outputs={"Out": [out.name]}, attrs=attrs)
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None):
    return image_resize(input, out_shape, scale, name, resample="BILINEAR")


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT side equals out_short_len (reference
    nn.py image_resize_short), preserving aspect ratio."""
    h, w = input.shape[2], input.shape[3]
    short, is_h = (h, True) if h < w else (w, False)
    ratio = out_short_len / float(short)
    out_shape = ([out_short_len, int(round(w * ratio))] if is_h
                 else [int(round(h * ratio)), out_short_len])
    return image_resize(input, out_shape=out_shape, resample=resample)


def crop(x, shape=None, offsets=None, name=None):
    helper = LayerHelper("crop", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    inputs = {"X": [x.name]}
    attrs = {}
    if isinstance(shape, ir.Variable):
        inputs["Y"] = [shape.name]
    else:
        attrs["shape"] = list(shape)
    if offsets is not None:
        attrs["offsets"] = list(offsets)
    helper.append_op("crop", inputs=inputs, outputs={"Out": [out.name]},
                     attrs=attrs)
    return out


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op("random_crop", inputs={"X": [x.name]},
                     outputs={"Out": [out.name]},
                     attrs={"shape": list(shape)})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32",
                 name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    inputs = {"X": [label.name]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist.name]
    helper.append_op("label_smooth", inputs=inputs,
                     outputs={"Out": [out.name]},
                     attrs={"epsilon": float(epsilon)})
    return out


def multiplex(inputs, index):
    helper = LayerHelper("multiplex")
    out = helper.create_variable_for_type_inference(dtype=inputs[0].dtype)
    helper.append_op("multiplex",
                     inputs={"X": [v.name for v in inputs],
                             "Ids": [index.name]},
                     outputs={"Out": [out.name]})
    return out


def rank_loss(label, left, right, name=None):
    helper = LayerHelper("rank_loss", name=name)
    out = helper.create_variable_for_type_inference(dtype=left.dtype)
    helper.append_op("rank_loss",
                     inputs={"Label": [label.name], "Left": [left.name],
                             "Right": [right.name]},
                     outputs={"Out": [out.name]})
    return out


def dice_loss(input, label, epsilon=1e-5):
    """reference nn.py dice_loss — composed from elementwise layers the
    same way the reference composes it (math_op_patch overloads)."""
    from . import ops as _ops
    from .tensor import cast
    label_f = cast(label, input.dtype)
    # per-sample dice averaged over the batch (reference nn.py:4843-4851
    # reduces over dims 1.. then reduce_mean) — a global pool would let
    # large masks dominate small ones
    dims = list(range(1, len(input.shape)))
    inter = reduce_sum(_ops.elementwise_mul(input, label_f), dim=dims)
    union = reduce_sum(input, dim=dims) + reduce_sum(label_f, dim=dims)
    dice = scale(inter, scale=2.0) / (union + epsilon)
    return reduce_mean(scale(dice, scale=-1.0, bias=1.0))


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference(dtype="float32")
    wrong = helper.create_variable_for_type_inference(dtype="int32")
    correct = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op("mean_iou",
                     inputs={"Predictions": [input.name],
                             "Labels": [label.name]},
                     outputs={"OutMeanIou": [miou.name],
                              "OutWrong": [wrong.name],
                              "OutCorrect": [correct.name]},
                     attrs={"num_classes": int(num_classes)})
    return miou, wrong, correct


def roi_pool(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0):
    helper = LayerHelper("roi_pool")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op("roi_pool",
                     inputs={"X": [input.name], "ROIs": [rois.name]},
                     outputs={"Out": [out.name]},
                     attrs={"pooled_height": int(pooled_height),
                            "pooled_width": int(pooled_width),
                            "spatial_scale": float(spatial_scale)})
    return out


def ctc_greedy_decoder(input, blank, name=None):
    """reference nn.py ctc_greedy_decoder. Returns padded ids [B, T]; the
    decoded lengths ride the @SEQLEN companion (reference emits LoD)."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    out = helper.create_variable_for_type_inference(dtype="int32")
    lens = helper.create_variable_for_type_inference(dtype="int32")
    inputs = _seq_inputs(helper, input)
    helper.append_op("ctc_greedy_decoder", inputs=inputs,
                     outputs={"Out": [out.name], "OutLen": [lens.name]},
                     attrs={"blank": int(blank)})
    out.lod_level = 1
    blk = helper.main_program.current_block()
    comp = blk.create_var(name=seqlen_var_name(out.name), shape=[-1],
                          dtype="int32")
    helper.append_op("assign", inputs={"X": [lens.name]},
                     outputs={"Out": [comp.name]})
    return out, lens


def lod_reset(x, y=None, target_lod=None):
    helper = LayerHelper("lod_reset")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    inputs = {"X": [x.name]}
    attrs = {}
    if y is not None:
        inputs["Y"] = [y.name]
    elif target_lod is not None:
        attrs["target_lod"] = list(target_lod)
    else:
        raise ValueError("lod_reset needs y or target_lod")
    helper.append_op("lod_reset", inputs=inputs,
                     outputs={"Out": [out.name]}, attrs=attrs)
    out.lod_level = max(1, x.lod_level)
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None):
    """reference nn.py chunk_eval -> (precision, recall, f1, #infer,
    #label, #correct)."""
    helper = LayerHelper("chunk_eval")
    names = ["Precision", "Recall", "F1-Score", "NumInferChunks",
             "NumLabelChunks", "NumCorrectChunks"]
    dtypes = ["float32", "float32", "float32", "int32", "int32", "int32"]
    outs = {s: [helper.create_variable_for_type_inference(dtype=d).name]
            for s, d in zip(names, dtypes)}
    inputs = _seq_inputs(helper, input, {"Label": [label.name]})
    helper.append_op("chunk_eval", inputs=inputs, outputs=outs,
                     attrs={"num_chunk_types": int(num_chunk_types),
                            "chunk_scheme": chunk_scheme,
                            "excluded_chunk_types":
                                list(excluded_chunk_types or [])})
    blk = helper.main_program.current_block()
    return tuple(blk.var(outs[s][0]) for s in names)


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """Single LSTM step (reference nn.py lstm_unit:2819): fc on
    [x_t, h_prev] then the lstm_unit op."""
    helper = LayerHelper("lstm_unit", **locals())
    size = cell_t_prev.shape[-1]
    from .tensor import concat
    cat = concat([x_t, hidden_t_prev], axis=1)
    fc_out = fc(input=cat, size=4 * size, param_attr=param_attr,
                bias_attr=bias_attr if bias_attr is not None else None)
    h = helper.create_variable_for_type_inference(dtype=x_t.dtype)
    c = helper.create_variable_for_type_inference(dtype=x_t.dtype)
    helper.append_op("lstm_unit",
                     inputs={"X": [fc_out.name], "C_prev": [cell_t_prev.name]},
                     outputs={"H": [h.name], "C": [c.name]},
                     attrs={"forget_bias": float(forget_bias)})
    return h, c


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=True, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """LSTM with recurrent projection (reference nn.py dynamic_lstmp,
    including its use_peepholes=True default).
    `input`: [B, T, 4*hidden] x-projections, as for dynamic_lstm."""
    helper = LayerHelper("lstmp", **locals())
    hidden_size = size // 4
    bias_cols = 7 * hidden_size if use_peepholes else 4 * hidden_size
    weight = helper.create_parameter(param_attr,
                                     [proj_size, 4 * hidden_size], dtype)
    proj_weight = helper.create_parameter(param_attr,
                                          [hidden_size, proj_size], dtype)
    bias = helper.create_parameter(helper.bias_attr, [1, bias_cols],
                                   dtype, is_bias=True) \
        if bias_attr is not False else None
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    inputs = {"Input": [input.name], "Weight": [weight.name],
              "ProjWeight": [proj_weight.name]}
    if bias is not None:
        inputs["Bias"] = [bias.name]
    seq = helper.ensure_seqlen_var(input)
    if seq is not None:
        inputs["SeqLen"] = [seq.name]
    helper.append_op("lstmp", inputs=inputs,
                     outputs={"Projection": [proj.name], "Cell": [cell.name]},
                     attrs={"use_peepholes": use_peepholes,
                            "is_reverse": is_reverse,
                            "gate_activation": gate_activation,
                            "cell_activation": cell_activation,
                            "candidate_activation": candidate_activation,
                            "proj_activation": proj_activation})
    proj.lod_level = cell.lod_level = input.lod_level
    return proj, cell


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable int64 counter bumped once per executor run (reference
    nn.py autoincreased_step_counter, used by learning-rate schedulers)."""
    helper = LayerHelper("global_step_counter")
    name = counter_name or "@STEP_COUNTER@"
    blk = helper.main_program.global_block()
    if name in blk.vars:
        # idempotent (reference guards with is_new_var): a second caller
        # shares the counter instead of double-stepping it
        return blk.vars[name]
    counter = helper.create_global_variable(
        name=name, shape=[1], dtype="int64", persistable=True)
    helper.set_variable_initializer(counter,
                                    init.ConstantInitializer(begin - step))
    helper.append_op("increment", inputs={"X": [counter.name]},
                     outputs={"Out": [counter.name]},
                     attrs={"step": float(step)})
    counter.stop_gradient = True
    return counter


def beam_search(pre_ids, pre_scores, probs, beam_size, end_id, name=None,
                finished=None):
    """Static-shape beam expansion (reference nn.py beam_search:2657; the
    reference works on LoD beams, this build on dense [B, beam] state —
    same selection semantics, TPU-static shapes). `probs` are log-probs
    [B, beam, V]; returns (selected_ids, parents, new_scores, new_finished).
    See models/machine_translation.py for the full decode loop."""
    helper = LayerHelper("beam_search", name=name)
    if finished is None:
        raise ValueError("pass the running `finished` [B, beam] bool var")
    outs = {k: [helper.create_variable_for_type_inference(dtype=d).name]
            for k, d in (("Ids", "int32"), ("Parents", "int32"),
                         ("AccScoresOut", probs.dtype),
                         ("FinishedOut", "bool"))}
    helper.append_op("beam_search_step",
                     inputs={"LogProbs": [probs.name],
                             "AccScores": [pre_scores.name],
                             "Finished": [finished.name]},
                     outputs=outs,
                     attrs={"beam_size": int(beam_size),
                            "end_id": int(end_id)})
    blk = helper.main_program.current_block()
    return tuple(blk.var(outs[k][0])
                 for k in ("Ids", "Parents", "AccScoresOut", "FinishedOut"))


def beam_search_decode(ids_hist, parents_hist, final_scores, beam_size=None,
                       end_id=None, name=None):
    """Backtrack stacked beam selections into ranked sequences (reference
    nn.py beam_search_decode / beam_search_decode_op.cc). ids_hist /
    parents_hist: [B, T, beam]; returns (sentence_ids [B, beam, T],
    sentence_scores [B, beam]) best-first."""
    helper = LayerHelper("beam_search_decode", name=name)
    ids = helper.create_variable_for_type_inference(dtype="int32")
    scores = helper.create_variable_for_type_inference(
        dtype=final_scores.dtype)
    helper.append_op("beam_backtrack",
                     inputs={"Ids": [ids_hist.name],
                             "Parents": [parents_hist.name],
                             "AccScores": [final_scores.name]},
                     outputs={"SentenceIds": [ids.name],
                              "SentenceScores": [scores.name]})
    blk = helper.main_program.current_block()
    return ids, scores


def sequence_slice(input, offset, length, name=None):
    """Per-sequence sub-slices (reference sequence_slice_op.cc): row b of
    the output is input[b, offset_b : offset_b + length_b], left-aligned
    in the padded layout; the slice lengths ride the @SEQLEN companion.
    Runtime lengths clamp to the padded bound (an XLA program cannot
    raise on traced values; the reference host-asserts instead)."""
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    lens = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op("sequence_slice",
                     inputs={"X": [input.name], "Offset": [offset.name],
                             "Length": [length.name]},
                     outputs={"Out": [out.name], "OutLen": [lens.name]},
                     attrs={"nested": input.lod_level >= 2})
    out.lod_level = max(input.lod_level, 1)
    blk = helper.main_program.current_block()
    inner = out.lod_level - 1
    comp = blk.create_var(name=seqlen_var_name(out.name, inner),
                          shape=[-1] * (inner + 1), dtype="int32")
    helper.append_op("assign", inputs={"X": [lens.name]},
                     outputs={"Out": [comp.name]})
    for level in range(inner):      # outer doc counts ride through
        src = helper.ensure_seqlen_var(input, level=level)
        if src is not None:
            dst = helper.ensure_seqlen_var(out, level=level)
            helper.append_op("assign", inputs={"X": [src.name]},
                             outputs={"Out": [dst.name]})
    return out


def sequence_erase(input, tokens, name=None):
    """Remove `tokens` from each sequence and compact left (reference
    sequence_erase_op.cc; used by edit_distance preprocessing). The
    shrunken lengths ride the @SEQLEN companion."""
    helper = LayerHelper("sequence_erase", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    lens = helper.create_variable_for_type_inference(dtype="int32")
    inputs = _seq_inputs(helper, input)
    helper.append_op("sequence_erase", inputs=inputs,
                     outputs={"Out": [out.name], "OutLen": [lens.name]},
                     attrs={"tokens": [int(t) for t in tokens]})
    out.lod_level = max(input.lod_level, 1)
    blk = helper.main_program.current_block()
    inner = out.lod_level - 1
    comp = blk.create_var(name=seqlen_var_name(out.name, inner),
                          shape=[-1] * (inner + 1), dtype="int32")
    helper.append_op("assign", inputs={"X": [lens.name]},
                     outputs={"Out": [comp.name]})
    for level in range(inner):      # outer doc counts ride through
        src = helper.ensure_seqlen_var(input, level=level)
        if src is not None:
            dst = helper.ensure_seqlen_var(out, level=level)
            helper.append_op("assign", inputs={"X": [src.name]},
                             outputs={"Out": [dst.name]})
    return out
