"""Detection layers DSL (reference: python/paddle/fluid/layers/detection.py
— prior_box :likely, multi_box_head, bipartite_match, target_assign,
ssd_loss, detection_output, box_coder, iou_similarity, anchor_generator,
polygon_box_transform). Op lowerings in ops/detection.py document the
TPU-native static-shape redesign (masks/counts instead of LoD outputs)."""

from __future__ import annotations

from ..layer_helper import LayerHelper
from . import nn as _nn
from . import tensor as _t


def _op(helper, type, inputs, out_slots, attrs=None, dtypes=None):
    outs = {}
    vars_ = []
    for i, slot in enumerate(out_slots):
        dt = (dtypes or {}).get(slot, "float32")
        v = helper.create_variable_for_type_inference(dtype=dt)
        outs[slot] = [v.name]
        vars_.append(v)
    helper.append_op(type, inputs=inputs, outputs=outs, attrs=attrs or {})
    return vars_


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    boxes, var = _op(helper, "prior_box",
                     {"Input": [input.name], "Image": [image.name]},
                     ("Boxes", "Variances"),
                     {"min_sizes": list(min_sizes),
                      "max_sizes": list(max_sizes or []),
                      "aspect_ratios": list(aspect_ratios),
                      "variances": list(variance), "flip": flip,
                      "clip": clip, "step_w": steps[0], "step_h": steps[1],
                      "offset": offset,
                      "min_max_aspect_ratios_order":
                          min_max_aspect_ratios_order})
    return boxes, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", name=name)
    anchors, var = _op(helper, "anchor_generator", {"Input": [input.name]},
                       ("Anchors", "Variances"),
                       {"anchor_sizes": list(anchor_sizes or [64, 128, 256]),
                        "aspect_ratios": list(aspect_ratios or [0.5, 1, 2]),
                        "variances": list(variance),
                        "stride": list(stride or [16.0, 16.0]),
                        "offset": offset})
    return anchors, var


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out, = _op(helper, "iou_similarity", {"X": [x.name], "Y": [y.name]},
               ("Out",))
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", name=name)
    inputs = {"PriorBox": [prior_box.name], "TargetBox": [target_box.name]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var.name]
    out, = _op(helper, "box_coder", inputs, ("OutputBox",),
               {"code_type": code_type, "box_normalized": box_normalized})
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    helper = LayerHelper("bipartite_match", name=name)
    idx, dist = _op(helper, "bipartite_match",
                    {"DistMat": [dist_matrix.name]},
                    ("ColToRowMatchIndices", "ColToRowMatchDist"),
                    {"match_type": match_type,
                     "dist_threshold": dist_threshold},
                    dtypes={"ColToRowMatchIndices": "int32"})
    return idx, dist


def target_assign(input, matched_indices, negative_mask=None,
                  mismatch_value=0.0, name=None):
    helper = LayerHelper("target_assign", name=name)
    inputs = {"X": [input.name], "MatchIndices": [matched_indices.name]}
    if negative_mask is not None:
        inputs["NegMask"] = [negative_mask.name]
    out, weight = _op(helper, "target_assign", inputs,
                      ("Out", "OutWeight"),
                      {"mismatch_value": float(mismatch_value)})
    return out, weight


def multiclass_nms(bboxes, scores, score_threshold=0.01, nms_top_k=400,
                   keep_top_k=200, nms_threshold=0.3, background_label=0,
                   nms_eta=1.0, normalized=True, name=None):
    """Static-shape NMS: Out [B, keep_top_k, 6] padded with label=-1 plus
    Count [B] (reference emits LoD; see ops/detection.py)."""
    helper = LayerHelper("multiclass_nms", name=name)
    out, count = _op(helper, "multiclass_nms",
                     {"BBoxes": [bboxes.name], "Scores": [scores.name]},
                     ("Out", "Count"),
                     {"score_threshold": score_threshold,
                      "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                      "nms_threshold": nms_threshold,
                      "background_label": background_label,
                      "nms_eta": nms_eta, "normalized": normalized},
                     dtypes={"Count": "int32"})
    return out, count


detection_output = multiclass_nms  # reference detection_output wraps
# box_coder decode + multiclass_nms; compose explicitly when deltas are fed


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", name=name)
    out, = _op(helper, "polygon_box_transform", {"Input": [input.name]},
               ("Output",))
    return out


def mine_hard_examples(cls_loss, match_indices, loc_loss=None,
                       match_dist=None, neg_pos_ratio=3.0,
                       neg_dist_threshold=0.5, name=None):
    helper = LayerHelper("mine_hard_examples", name=name)
    inputs = {"ClsLoss": [cls_loss.name],
              "MatchIndices": [match_indices.name]}
    if loc_loss is not None:
        inputs["LocLoss"] = [loc_loss.name]
    if match_dist is not None:
        inputs["MatchDist"] = [match_dist.name]
    neg, upd = _op(helper, "mine_hard_examples", inputs,
                   ("NegMask", "UpdatedMatchIndices"),
                   {"neg_pos_ratio": neg_pos_ratio,
                    "neg_dist_threshold": neg_dist_threshold},
                   dtypes={"NegMask": "int32",
                           "UpdatedMatchIndices": "int32"})
    return neg, upd


def rpn_target_assign(anchor_box, gt_box, dist_matrix,
                      rpn_batch_size_per_im=256, rpn_fg_fraction=0.5,
                      rpn_positive_overlap=0.7, rpn_negative_overlap=0.3,
                      name=None):
    helper = LayerHelper("rpn_target_assign", name=name)
    labels, match = _op(helper, "rpn_target_assign",
                        {"Anchor": [anchor_box.name],
                         "GtBox": [gt_box.name],
                         "DistMat": [dist_matrix.name]},
                        ("Labels", "MatchIndices"),
                        {"rpn_batch_size_per_im": rpn_batch_size_per_im,
                         "rpn_fg_fraction": rpn_fg_fraction,
                         "rpn_positive_overlap": rpn_positive_overlap,
                         "rpn_negative_overlap": rpn_negative_overlap},
                        dtypes={"Labels": "int32", "MatchIndices": "int32"})
    return labels, match


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, loc_loss_weight=1.0, conf_loss_weight=1.0,
             mismatch_value=0.0, name=None):
    """SSD multibox loss (reference detection.py ssd_loss): match priors to
    gt (bipartite + per_prediction), mine hard negatives, localization
    smooth-L1 on matched priors + confidence cross-entropy on matched and
    mined-negative priors. gt_box [B, N, 4], gt_label [B, N, 1] (padded
    rows get label 0 = background), location [B, M, 4] deltas,
    confidence [B, M, C], prior_box [M, 4]."""
    from . import ops as lops

    helper = LayerHelper("ssd_loss", name=name)
    iou = iou_similarity(gt_box, prior_box)               # [B, N, M]
    match_idx, match_dist = bipartite_match(
        iou, match_type="per_prediction",
        dist_threshold=overlap_threshold)                 # [B, M]

    # encode gt boxes onto priors per image, gathered by the match
    gt_on_prior, loc_weight = target_assign(
        gt_box, match_idx, mismatch_value=mismatch_value)  # [B, M, 4]
    enc_gt = _encode_per_prior(helper, gt_on_prior, prior_box,
                               prior_box_var)

    loc_diff = lops.elementwise_sub(location, enc_gt)
    loc_l = _smooth_l1(loc_diff)
    loc_l = lops.elementwise_mul(
        _nn.reduce_sum(loc_l, dim=[2]), _squeeze_w(loc_weight))

    # confidence loss: softmax CE against assigned labels
    lbl_on_prior, _ = target_assign(gt_label, match_idx,
                                    mismatch_value=background_label)
    conf_l = _softmax_ce_per_prior(confidence, lbl_on_prior)   # [B, M]
    neg_mask, _ = mine_hard_examples(conf_l, match_idx,
                                     match_dist=match_dist,
                                     neg_pos_ratio=neg_pos_ratio,
                                     neg_dist_threshold=overlap_threshold)
    pos = _match_mask(helper, match_idx)
    keep = lops.elementwise_add(pos, _t.cast(neg_mask, "float32"))
    conf_l = lops.elementwise_mul(conf_l, keep)

    total = lops.elementwise_add(
        _nn.scale(loc_l, scale=loc_loss_weight),
        _nn.scale(conf_l, scale=conf_loss_weight))
    return total


# --- small graph helpers used by ssd_loss ---------------------------------

def _encode_per_prior(helper, gt_on_prior, prior_box, prior_box_var):
    out, = _op(helper, "box_encode_per_prior",
               {"TargetBox": [gt_on_prior.name],
                "PriorBox": [prior_box.name]}
               | ({"PriorBoxVar": [prior_box_var.name]}
                  if prior_box_var is not None else {}),
               ("OutputBox",))
    return out


def _squeeze_w(w):
    return _nn.reduce_sum(w, dim=[2])


def _match_mask(helper, match_idx):
    ge = _op(helper, "greater_equal_scalar0",
             {"X": [match_idx.name]}, ("Out",), dtypes={"Out": "float32"})
    return ge[0]


def _smooth_l1(absdiff):
    helper = LayerHelper("smooth_l1_elem")
    out, = _op(helper, "smooth_l1_elementwise", {"X": [absdiff.name]},
               ("Out",))
    return out


def _softmax_ce_per_prior(confidence, labels):
    helper = LayerHelper("conf_ce")
    out, = _op(helper, "softmax_ce_no_reduce",
               {"Logits": [confidence.name], "Label": [labels.name]},
               ("Out",))
    return out
