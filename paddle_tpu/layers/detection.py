"""Detection layers DSL (reference: python/paddle/fluid/layers/detection.py
— prior_box :likely, multi_box_head, bipartite_match, target_assign,
ssd_loss, detection_output, box_coder, iou_similarity, anchor_generator,
polygon_box_transform). Op lowerings in ops/detection.py document the
TPU-native static-shape redesign (masks/counts instead of LoD outputs)."""

from __future__ import annotations

from ..layer_helper import LayerHelper
from . import nn as _nn
from . import tensor as _t


def _op(helper, type, inputs, out_slots, attrs=None, dtypes=None):
    outs = {}
    vars_ = []
    for i, slot in enumerate(out_slots):
        dt = (dtypes or {}).get(slot, "float32")
        v = helper.create_variable_for_type_inference(dtype=dt)
        outs[slot] = [v.name]
        vars_.append(v)
    helper.append_op(type, inputs=inputs, outputs=outs, attrs=attrs or {})
    return vars_


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    helper = LayerHelper("prior_box", name=name)
    boxes, var = _op(helper, "prior_box",
                     {"Input": [input.name], "Image": [image.name]},
                     ("Boxes", "Variances"),
                     {"min_sizes": list(min_sizes),
                      "max_sizes": list(max_sizes or []),
                      "aspect_ratios": list(aspect_ratios),
                      "variances": list(variance), "flip": flip,
                      "clip": clip, "step_w": steps[0], "step_h": steps[1],
                      "offset": offset,
                      "min_max_aspect_ratios_order":
                          min_max_aspect_ratios_order})
    return boxes, var


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None, offset=0.5,
                     name=None):
    helper = LayerHelper("anchor_generator", name=name)
    anchors, var = _op(helper, "anchor_generator", {"Input": [input.name]},
                       ("Anchors", "Variances"),
                       {"anchor_sizes": list(anchor_sizes or [64, 128, 256]),
                        "aspect_ratios": list(aspect_ratios or [0.5, 1, 2]),
                        "variances": list(variance),
                        "stride": list(stride or [16.0, 16.0]),
                        "offset": offset})
    return anchors, var


def iou_similarity(x, y, name=None):
    helper = LayerHelper("iou_similarity", name=name)
    out, = _op(helper, "iou_similarity", {"X": [x.name], "Y": [y.name]},
               ("Out",))
    return out


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None):
    helper = LayerHelper("box_coder", name=name)
    inputs = {"PriorBox": [prior_box.name], "TargetBox": [target_box.name]}
    if prior_box_var is not None:
        inputs["PriorBoxVar"] = [prior_box_var.name]
    out, = _op(helper, "box_coder", inputs, ("OutputBox",),
               {"code_type": code_type, "box_normalized": box_normalized})
    return out


def bipartite_match(dist_matrix, match_type="bipartite",
                    dist_threshold=0.5, name=None):
    helper = LayerHelper("bipartite_match", name=name)
    idx, dist = _op(helper, "bipartite_match",
                    {"DistMat": [dist_matrix.name]},
                    ("ColToRowMatchIndices", "ColToRowMatchDist"),
                    {"match_type": match_type,
                     "dist_threshold": dist_threshold},
                    dtypes={"ColToRowMatchIndices": "int32"})
    return idx, dist


def target_assign(input, matched_indices, negative_mask=None,
                  mismatch_value=0.0, name=None):
    helper = LayerHelper("target_assign", name=name)
    inputs = {"X": [input.name], "MatchIndices": [matched_indices.name]}
    if negative_mask is not None:
        inputs["NegMask"] = [negative_mask.name]
    out, weight = _op(helper, "target_assign", inputs,
                      ("Out", "OutWeight"),
                      {"mismatch_value": float(mismatch_value)})
    return out, weight


def multiclass_nms(bboxes, scores, score_threshold=0.01, nms_top_k=400,
                   keep_top_k=200, nms_threshold=0.3, background_label=0,
                   nms_eta=1.0, normalized=True, name=None):
    """Static-shape NMS: Out [B, keep_top_k, 6] padded with label=-1 plus
    Count [B] (reference emits LoD; see ops/detection.py)."""
    helper = LayerHelper("multiclass_nms", name=name)
    out, count = _op(helper, "multiclass_nms",
                     {"BBoxes": [bboxes.name], "Scores": [scores.name]},
                     ("Out", "Count"),
                     {"score_threshold": score_threshold,
                      "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                      "nms_threshold": nms_threshold,
                      "background_label": background_label,
                      "nms_eta": nms_eta, "normalized": normalized},
                     dtypes={"Count": "int32"})
    return out, count


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     name=None):
    """Decode predicted deltas against priors, then NMS (reference
    detection.py detection_output = box_coder(decode_center_size) +
    multiclass_nms). loc [B,M,4], scores [B,M,C] (softmax-ed here, as the
    reference does), priors [M,4]."""
    from .. import layers as _layers
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    probs = _layers.transpose(_nn.softmax(scores), perm=[0, 2, 1])
    return multiclass_nms(decoded, probs,
                          score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold,
                          background_label=background_label,
                          nms_eta=nms_eta, name=name)


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD prediction head (reference detection.py multi_box_head): per
    feature map, a prior_box + 3x3 convs for location and confidence;
    outputs concatenated over maps. Returns (mbox_locs [B,M,4],
    mbox_confs [B,M,C], boxes [M,4], variances [M,4])."""
    from .. import layers as _layers

    n = len(inputs)
    if not min_sizes:
        # the reference's ratio schedule (detection.py multi_box_head):
        # sizes evenly spaced in [min_ratio, max_ratio]% of base_size,
        # with a fixed 10%/20% pair prepended for the first map
        if n <= 2 or min_ratio is None or max_ratio is None:
            raise ValueError("multi_box_head: give min_sizes or "
                             "min_ratio/max_ratio with >2 inputs")
        min_sizes, max_sizes = [], []
        step = int((max_ratio - min_ratio) / (n - 2))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes
        max_sizes = [base_size * 0.20] + max_sizes

    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, feat in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        mins_list = list(mins) if isinstance(mins, (list, tuple)) else [mins]
        if maxs is not None:
            maxs_list = (list(maxs) if isinstance(maxs, (list, tuple))
                         else [maxs])
            # prior_box pairs max_sizes[s] with min_sizes[s]; a length
            # mismatch would mis-split the loc/conf conv channels
            if len(maxs_list) != len(mins_list):
                raise ValueError(
                    "multi_box_head: layer %d supplies %d min_sizes but %d "
                    "max_sizes; they must pair one-to-one"
                    % (i, len(mins_list), len(maxs_list)))
        else:
            maxs_list = None
        ars = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        st = steps[i] if steps else [
            step_w[i] if step_w else 0.0, step_h[i] if step_h else 0.0]
        box, var = prior_box(
            feat, image, mins_list, maxs_list,
            ars, variance, flip, clip,
            st if isinstance(st, (list, tuple)) else [st, st], offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        # must match the prior_box op's count exactly for the conv channel
        # split to line up — use the op's own expansion, never a copy
        from ..ops.detection import _expand_aspect_ratios
        expanded = _expand_aspect_ratios(ars, flip)
        num_priors = (len(expanded) + (1 if maxs_list else 0)) * len(mins_list)
        loc = _nn.conv2d(input=feat, num_filters=num_priors * 4,
                         filter_size=kernel_size, padding=pad, stride=stride)
        loc = _layers.transpose(loc, perm=[0, 2, 3, 1])
        loc = _layers.reshape(loc, shape=[0, -1, 4])
        locs.append(loc)
        conf = _nn.conv2d(input=feat, num_filters=num_priors * num_classes,
                          filter_size=kernel_size, padding=pad, stride=stride)
        conf = _layers.transpose(conf, perm=[0, 2, 3, 1])
        conf = _layers.reshape(conf, shape=[0, -1, num_classes])
        confs.append(conf)
        boxes_l.append(_layers.reshape(box, shape=[-1, 4]))
        vars_l.append(_layers.reshape(var, shape=[-1, 4]))

    mbox_locs = _t.concat(locs, axis=1)
    mbox_confs = _t.concat(confs, axis=1)
    boxes = _t.concat(boxes_l, axis=0)
    variances = _t.concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  ap_version="integral", name=None):
    """Per-batch mean average precision (reference detection_map_op.cc).
    detect_res [B,D,6] (label, score, x1,y1,x2,y2; label=-1 padding, the
    multiclass_nms output layout), label [B,G,6] ground truth
    (label, difficult, x1,y1,x2,y2) padded with label=-1."""
    helper = LayerHelper("detection_map", name=name)
    out, = _op(helper, "detection_map",
               {"DetectRes": [detect_res.name], "Label": [label.name]},
               ("MAP",),
               {"class_num": class_num, "background_label": background_label,
                "overlap_threshold": overlap_threshold,
                "evaluate_difficult": evaluate_difficult,
                "ap_version": ap_version})
    return out


def polygon_box_transform(input, name=None):
    helper = LayerHelper("polygon_box_transform", name=name)
    out, = _op(helper, "polygon_box_transform", {"Input": [input.name]},
               ("Output",))
    return out


def mine_hard_examples(cls_loss, match_indices, loc_loss=None,
                       match_dist=None, neg_pos_ratio=3.0,
                       neg_dist_threshold=0.5, name=None):
    helper = LayerHelper("mine_hard_examples", name=name)
    inputs = {"ClsLoss": [cls_loss.name],
              "MatchIndices": [match_indices.name]}
    if loc_loss is not None:
        inputs["LocLoss"] = [loc_loss.name]
    if match_dist is not None:
        inputs["MatchDist"] = [match_dist.name]
    neg, upd = _op(helper, "mine_hard_examples", inputs,
                   ("NegMask", "UpdatedMatchIndices"),
                   {"neg_pos_ratio": neg_pos_ratio,
                    "neg_dist_threshold": neg_dist_threshold},
                   dtypes={"NegMask": "int32",
                           "UpdatedMatchIndices": "int32"})
    return neg, upd


def rpn_target_assign(anchor_box, gt_box, dist_matrix,
                      rpn_batch_size_per_im=256, rpn_fg_fraction=0.5,
                      rpn_positive_overlap=0.7, rpn_negative_overlap=0.3,
                      name=None):
    helper = LayerHelper("rpn_target_assign", name=name)
    labels, match = _op(helper, "rpn_target_assign",
                        {"Anchor": [anchor_box.name],
                         "GtBox": [gt_box.name],
                         "DistMat": [dist_matrix.name]},
                        ("Labels", "MatchIndices"),
                        {"rpn_batch_size_per_im": rpn_batch_size_per_im,
                         "rpn_fg_fraction": rpn_fg_fraction,
                         "rpn_positive_overlap": rpn_positive_overlap,
                         "rpn_negative_overlap": rpn_negative_overlap},
                        dtypes={"Labels": "int32", "MatchIndices": "int32"})
    return labels, match


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, loc_loss_weight=1.0, conf_loss_weight=1.0,
             mismatch_value=0.0, name=None):
    """SSD multibox loss (reference detection.py ssd_loss): match priors to
    gt (bipartite + per_prediction), mine hard negatives, localization
    smooth-L1 on matched priors + confidence cross-entropy on matched and
    mined-negative priors. gt_box [B, N, 4], gt_label [B, N, 1] (padded
    rows get label 0 = background), location [B, M, 4] deltas,
    confidence [B, M, C], prior_box [M, 4]."""
    from . import ops as lops

    helper = LayerHelper("ssd_loss", name=name)
    iou = iou_similarity(gt_box, prior_box)               # [B, N, M]
    match_idx, match_dist = bipartite_match(
        iou, match_type="per_prediction",
        dist_threshold=overlap_threshold)                 # [B, M]

    # encode gt boxes onto priors per image, gathered by the match
    gt_on_prior, loc_weight = target_assign(
        gt_box, match_idx, mismatch_value=mismatch_value)  # [B, M, 4]
    enc_gt = _encode_per_prior(helper, gt_on_prior, prior_box,
                               prior_box_var)

    loc_diff = lops.elementwise_sub(location, enc_gt)
    loc_l = _smooth_l1(loc_diff)
    loc_l = lops.elementwise_mul(
        _nn.reduce_sum(loc_l, dim=[2]), _squeeze_w(loc_weight))

    # confidence loss: softmax CE against assigned labels
    lbl_on_prior, _ = target_assign(gt_label, match_idx,
                                    mismatch_value=background_label)
    conf_l = _softmax_ce_per_prior(confidence, lbl_on_prior)   # [B, M]
    neg_mask, _ = mine_hard_examples(conf_l, match_idx,
                                     match_dist=match_dist,
                                     neg_pos_ratio=neg_pos_ratio,
                                     neg_dist_threshold=overlap_threshold)
    pos = _match_mask(helper, match_idx)
    keep = lops.elementwise_add(pos, _t.cast(neg_mask, "float32"))
    conf_l = lops.elementwise_mul(conf_l, keep)

    total = lops.elementwise_add(
        _nn.scale(loc_l, scale=loc_loss_weight),
        _nn.scale(conf_l, scale=conf_loss_weight))
    return total


# --- small graph helpers used by ssd_loss ---------------------------------

def _encode_per_prior(helper, gt_on_prior, prior_box, prior_box_var):
    out, = _op(helper, "box_encode_per_prior",
               {"TargetBox": [gt_on_prior.name],
                "PriorBox": [prior_box.name]}
               | ({"PriorBoxVar": [prior_box_var.name]}
                  if prior_box_var is not None else {}),
               ("OutputBox",))
    return out


def _squeeze_w(w):
    return _nn.reduce_sum(w, dim=[2])


def _match_mask(helper, match_idx):
    ge = _op(helper, "greater_equal_scalar0",
             {"X": [match_idx.name]}, ("Out",), dtypes={"Out": "float32"})
    return ge[0]


def _smooth_l1(absdiff):
    helper = LayerHelper("smooth_l1_elem")
    out, = _op(helper, "smooth_l1_elementwise", {"X": [absdiff.name]},
               ("Out",))
    return out


def _softmax_ce_per_prior(confidence, labels):
    helper = LayerHelper("conf_ce")
    out, = _op(helper, "softmax_ce_no_reduce",
               {"Logits": [confidence.name], "Label": [labels.name]},
               ("Out",))
    return out
