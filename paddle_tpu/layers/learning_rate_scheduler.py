"""Learning-rate schedules as in-graph ops (reference:
python/paddle/fluid/layers/learning_rate_scheduler.py).

Each schedule builds a tiny subgraph reading a persistable global step counter
(incremented once per optimizer pass) — same design as the reference; on TPU
the whole schedule fuses into the update step.
"""

from __future__ import annotations

import math

from ..layer_helper import LayerHelper
from .. import initializer as init
from . import tensor, ops, nn


def _global_step(helper: LayerHelper):
    gb = helper.main_program.global_block()
    name = "@LR_DECAY_COUNTER@"
    if name in gb.vars:
        return gb.vars[name]
    var = gb.create_var(name=name, shape=(1,), dtype="float32", persistable=True,
                        stop_gradient=True)
    helper.set_variable_initializer(var, init.ConstantInitializer(0.0))
    return var


def _increment_global_step(helper, step):
    out_name = step.name
    helper.append_op("increment", inputs={"X": [step.name]},
                     outputs={"Out": [out_name]}, attrs={"step": 1.0})
    return step


def global_learning_rate_counter():
    helper = LayerHelper("lr_counter")
    return _global_step(helper)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    helper = LayerHelper("exponential_decay")
    step = _global_step(helper)
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return learning_rate * (decay_rate ** div)


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    helper = LayerHelper("natural_exp_decay")
    step = _global_step(helper)
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    return learning_rate * ops.exp(div * (-decay_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    helper = LayerHelper("inverse_time_decay")
    step = _global_step(helper)
    div = step / float(decay_steps)
    if staircase:
        div = ops.floor(div)
    denom = div * decay_rate + 1.0
    return tensor.fill_constant([1], "float32", learning_rate) / denom


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    helper = LayerHelper("polynomial_decay")
    step = _global_step(helper)
    if cycle:
        ratio = ops.ceil(step / float(decay_steps))
        ratio = ops.elementwise_max(ratio, tensor.fill_constant([1], "float32", 1.0))
        decay_var = ratio * float(decay_steps)
        frac = step / decay_var
    else:
        capped = ops.elementwise_min(step, tensor.fill_constant([1], "float32",
                                                                float(decay_steps)))
        frac = capped / float(decay_steps)
    one = tensor.fill_constant([1], "float32", 1.0)
    return (learning_rate - end_learning_rate) * ((one - frac) ** power) \
        + end_learning_rate


def piecewise_decay(boundaries, values):
    """Piecewise-constant lr: implemented as a sum of indicator windows so it
    stays branch-free inside the compiled step."""
    helper = LayerHelper("piecewise_decay")
    step = _global_step(helper)
    lr = tensor.fill_constant([1], "float32", 0.0)
    for i, v in enumerate(values):
        lo = boundaries[i - 1] if i > 0 else None
        hi = boundaries[i] if i < len(boundaries) else None
        ind = tensor.fill_constant([1], "float32", 1.0)
        if lo is not None:
            ind = ind * _ge_indicator(step, float(lo))
        if hi is not None:
            ind = ind * _lt_indicator(step, float(hi))
        lr = lr + ind * float(v)
    return lr


def _ge_indicator(step, bound):
    cmp = step >= tensor.fill_constant([1], "float32", bound)
    return tensor.cast(cmp, "float32")


def _lt_indicator(step, bound):
    cmp = step < tensor.fill_constant([1], "float32", bound)
    return tensor.cast(cmp, "float32")


def noam_decay(d_model, warmup_steps):
    """Transformer LR schedule (reference learning_rate_scheduler.py:44)."""
    helper = LayerHelper("noam_decay")
    step = _global_step(helper) + 1.0
    a = step ** -0.5
    b = step * (warmup_steps ** -1.5)
    return (d_model ** -0.5) * ops.elementwise_min(a, b)


def append_LARS(params_grads, learning_rate, weight_decay):
    """Layer-wise adaptive rate scaling (reference
    learning_rate_scheduler.py append_LARS): per-parameter
    lr = global_lr * ||w|| / (||g|| + weight_decay * ||w||), stored on the
    parameter's optimize_attr so Optimizer._create_param_lr picks it up."""
    from . import nn

    def _balanced_weight(param_norm, grad_norm):
        if weight_decay == 1.0:
            return grad_norm + param_norm
        return grad_norm + weight_decay * param_norm

    for param, grad in params_grads:
        param_lr = param.optimize_attr.get("learning_rate", 1.0)
        param_norm = ops.sqrt(nn.reduce_sum(ops.square(param)))
        grad_norm = ops.sqrt(nn.reduce_sum(ops.square(grad)))
        if isinstance(param_lr, float) and param_lr == 1.0:
            decayed_lr = learning_rate * param_norm \
                / _balanced_weight(param_norm, grad_norm)
        else:
            decayed_lr = learning_rate * param_lr * param_norm \
                / _balanced_weight(param_norm, grad_norm)
        param.optimize_attr["learning_rate"] = decayed_lr
