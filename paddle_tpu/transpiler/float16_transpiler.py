"""Half-precision inference transpiler.

Capability parity with the reference's float16 inference pass (reference:
paddle/contrib/float16/float16_transpiler.py — convert saved f32 weights,
rewrite the program's float vars to fp16, insert boundary casts; the demo
reports 1.9-3.3x V100 speedups, float16_benchmark.md).

TPU-native redesign: the half type defaults to **bfloat16** (the MXU's
native half — fp16 is also accepted); instead of per-op kernel-swap
bookkeeping, every float32 non-feed variable is re-typed and the
scope-resident parameters are converted in place, so the whole program
lowers to half-precision XLA ops. Fed f32 inputs are cast at the graph
boundary by an inserted `cast` op (the reference inserts the same
boundary casts). Use on INFERENCE programs (e.g. the result of
`fluid.io.load_inference_model`); training should use the executor's AMP
policy instead.
"""

from __future__ import annotations

import numpy as np

from ..core import ir


class Float16Transpiler:
    def transpile(self, program=None, place=None, scope=None,
                  dtype="bfloat16"):
        """Rewrite `program` (default main) to half precision in place and
        convert its parameters inside `scope` (default global)."""
        from ..core.executor import global_scope

        if dtype not in ("bfloat16", "float16"):
            raise ValueError(f"half dtype must be bfloat16 or float16, "
                             f"got {dtype!r}")
        program = program or ir.default_main_program()
        scope = scope or global_scope()
        block = program.global_block()

        # 1. fed data vars keep their f32 dtype; a boundary cast feeds the
        # half-precision graph (reference inserts the same casts). Only
        # vars some op actually READS get a cast — an unconditional cast
        # would turn ignorable leftover data vars into mandatory feeds.
        # Reads INSIDE control-flow sub-blocks count too: a data var
        # consumed only by a while/cond body would otherwise get no cast
        # and pull its raw f32 feed into the half graph (round-4/5
        # advisor) — same scan the Executor does for its read set.
        read_names = {n for op in block.ops
                      for names in op.inputs.values() for n in names}
        for op in block.ops:
            for si in ir.sub_block_indices(op):
                read_names.update(ir.external_reads(program, si))
        casted = {}
        new_ops = []
        consumed_data = [v for v in block.vars.values()
                         if v.is_data and v.dtype == "float32"
                         and v.name in read_names]
        for v in consumed_data:
            half = block.create_var(name=f"{v.name}.cast_fp16",
                                    shape=v.shape, dtype=dtype,
                                    stop_gradient=True)
            half.lod_level = v.lod_level
            casted[v.name] = half.name
            cast_op = ir.Operator(block, "cast",
                                  inputs={"X": [v.name]},
                                  outputs={"Out": [half.name]},
                                  attrs={"out_dtype": dtype})
            new_ops.append(cast_op)

        # 2. rewrite consumers to read the casted inputs — in EVERY block:
        # a sub-block op reading a fed f32 var directly would otherwise
        # pull the f32 feed into an otherwise-half graph (round-4 advisor)
        for blk in program.blocks:
            for op in blk.ops:
                for slot, names in op.inputs.items():
                    op.inputs[slot] = [casted.get(n, n) for n in names]

        block.ops[:] = new_ops + block.ops

        # 3. every other float32 var (params and temps) becomes half — in
        # EVERY block (control-flow sub-blocks included: a mixed-dtype
        # while carry would fail to lower), and ops that mint values from
        # a dtype attr (fill_constant, cast, ...) follow suit
        for blk in program.blocks:
            for v in blk.vars.values():
                if v.name in casted or v.is_data:
                    continue
                if v.dtype == "float32":
                    v.dtype = dtype
            for op in blk.ops:
                for key in ("dtype", "out_dtype"):
                    if str(op.attrs.get(key, "")) in ("float32", "fp32"):
                        op.attrs[key] = dtype

        # 4. convert the scope-resident parameters
        import jax.numpy as jnp

        np_half = jnp.bfloat16 if dtype == "bfloat16" else np.float16
        for name in list(scope.local_var_names()):
            var = block.vars.get(name)
            if var is None or var.is_data:
                continue
            val = scope.find_var(name)
            if (hasattr(val, "dtype")
                    and np.dtype(val.dtype) == np.float32):
                scope.set_var(name, np.asarray(val).astype(np_half))

        program._bump()
        return program
