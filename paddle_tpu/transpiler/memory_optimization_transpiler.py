"""Memory optimization (reference:
python/paddle/fluid/transpiler/memory_optimization_transpiler.py — liveness
analysis + var reuse, `memory_optimize` :373, `release_memory` :392).

TPU-native redesign: XLA's buffer assignment already performs liveness-based
reuse inside the compiled step, so the reference's var-sharing rewrite is
unnecessary. What still matters on TPU is *rematerialization* — trading
FLOPs for HBM on the backward pass. `memory_optimize` therefore marks ops
for `jax.checkpoint` (remat) at lowering: forward activations of marked ops
are recomputed in backward instead of being kept live by XLA.
"""

from __future__ import annotations

from ..core import ir

# ops whose outputs are cheap to recompute relative to their activation size
_DEFAULT_REMAT_TYPES = {"relu", "tanh", "sigmoid", "gelu", "softmax",
                        "dropout", "batch_norm", "layer_norm",
                        "elementwise_add", "elementwise_mul", "scale"}

REMAT_ATTR = "__remat__"


def memory_optimize(input_program: ir.Program, skip_opt_set=None,
                    print_log=False, level=0):
    """Mark cheap-to-recompute ops for rematerialization.

    level 0: activations only; level 1: also conv/matmul (maximum HBM
    savings, more recompute). The executor's grad lowering recomputes marked
    ops' forward inside the backward instead of holding the activation.
    """
    skip = set(skip_opt_set or ())
    types = set(_DEFAULT_REMAT_TYPES)
    if level >= 1:
        types |= {"conv2d", "mul", "matmul"}
    count = 0
    for block in input_program.blocks:
        for op in block.ops:
            if op.type in types and not (set(op.output_arg_names) & skip):
                op.attrs[REMAT_ATTR] = True
                count += 1
            # grad ops carry a deep-copied forward desc (made at backward
            # time); the mark must reach it or lowering never sees it
            fwd = op.attrs.get("__fwd_op__")
            if fwd is not None and fwd.get("type") in types \
                    and not (set(n for ns in fwd.get("outputs", {}).values()
                                 for n in ns) & skip):
                fwd.setdefault("attrs", {})[REMAT_ATTR] = True
    input_program._bump()
    if print_log:
        print(f"[memory_optimize] marked {count} ops for rematerialization")
    return input_program


def release_memory(input_program: ir.Program, skip_opt_set=None):
    """Reference `release_memory` inserted delete_var ops; on TPU, XLA frees
    buffers at their last use inside the step automatically, and the executor
    drops non-persistable env entries when the step returns. No-op for API
    parity."""
    return input_program
