"""Program transpilers (reference: python/paddle/fluid/transpiler/)."""

from .memory_optimization_transpiler import memory_optimize, release_memory  # noqa: F401
from .inference_transpiler import InferenceTranspiler  # noqa: F401
from .float16_transpiler import Float16Transpiler  # noqa: F401
from .distribute_transpiler import DistributeTranspiler, DistributeTranspilerConfig  # noqa: F401
from .ps_dispatcher import RoundRobin, HashName  # noqa: F401
