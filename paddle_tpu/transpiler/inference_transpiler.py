"""Inference graph rewrites (reference:
python/paddle/fluid/transpiler/inference_transpiler.py — `fuse_batch_norm`
:107 folds BN into the preceding conv's weights/bias; fuse_relu_mkldnn :63).

On TPU, XLA fuses BN math into the conv at compile time, so runtime speed
does not depend on this pass; it still exists for (a) API parity, (b)
shrinking saved inference models (BN params folded away), matching the
reference's deployment story.
"""

from __future__ import annotations

import numpy as np

from ..core import ir
from ..core.executor import global_scope


class InferenceTranspiler:
    def transpile(self, program: ir.Program, place=None, scope=None):
        scope = scope or global_scope()
        self._fuse_batch_norm(program, scope)
        return program

    def _fuse_batch_norm(self, program: ir.Program, scope):
        """Fold conv2d -> batch_norm(is_test) pairs: W' = W * gamma/std,
        b' = beta - gamma*mean/std (reference inference_transpiler.py:107)."""
        block = program.global_block()
        i = 0
        fused = 0
        while i < len(block.ops) - 1:
            op = block.ops[i]
            nxt = block.ops[i + 1]
            if (op.type == "conv2d" and nxt.type == "batch_norm"
                    and op.output("Output") and nxt.input("X")
                    and op.output("Output")[0] == nxt.input("X")[0]):
                w_name = op.input("Filter")[0]
                scale = np.asarray(scope.find_var(nxt.input("Scale")[0]))
                bias = np.asarray(scope.find_var(nxt.input("Bias")[0]))
                mean = np.asarray(scope.find_var(nxt.input("Mean")[0]))
                var = np.asarray(scope.find_var(nxt.input("Variance")[0]))
                w = np.asarray(scope.find_var(w_name))
                eps = nxt.attrs.get("epsilon", 1e-5)
                std = np.sqrt(var + eps)
                scope.set_var(w_name, w * (scale / std).reshape(-1, 1, 1, 1))
                conv_bias = 0.0
                if op.input("Bias"):
                    conv_bias = np.asarray(scope.find_var(op.input("Bias")[0]))
                new_bias = (conv_bias - mean) * scale / std + bias
                bias_name = w_name + "@bn_folded_bias"
                scope.set_var(bias_name, new_bias.astype(w.dtype))
                block.create_var(name=bias_name, shape=list(new_bias.shape),
                                 dtype=str(w.dtype), persistable=True)
                # rewrite: conv gains Bias, bn output aliases conv output
                op.inputs["Bias"] = [bias_name]
                op.outputs["Output"] = [nxt.output("Y")[0]]
                block.remove_op(i + 1)
                fused += 1
            i += 1
        program._bump()
        return fused
