"""Parameter placement dispatchers (reference:
python/paddle/fluid/transpiler/ps_dispatcher.py). On TPU these assign
parameter shards to mesh slices instead of pserver endpoints."""

from __future__ import annotations


class PSDispatcher:
    def __init__(self, eplist):
        self._eps = list(eplist)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class RoundRobin(PSDispatcher):
    def dispatch(self, varlist):
        out = []
        for v in varlist:
            out.append(self._eps[self._step % len(self._eps)])
            self._step += 1
        return out


class HashName(PSDispatcher):
    def dispatch(self, varlist):
        return [self._eps[hash(v.name if hasattr(v, "name") else str(v))
                          % len(self._eps)] for v in varlist]
