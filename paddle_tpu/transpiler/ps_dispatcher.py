"""Parameter placement dispatchers (reference:
python/paddle/fluid/transpiler/ps_dispatcher.py). On TPU these assign
parameter shards to mesh slices instead of pserver endpoints."""

from __future__ import annotations


class PSDispatcher:
    def __init__(self, eplist):
        self._eps = list(eplist)
        self._step = 0

    @property
    def eps(self):
        return self._eps

    def reset(self):
        self._step = 0

    def dispatch(self, varlist):
        raise NotImplementedError


class RoundRobin(PSDispatcher):
    def dispatch(self, varlist):
        return [self.dispatch_one(v) for v in varlist]

    def dispatch_one(self, var):
        ep = self._eps[self._step % len(self._eps)]
        self._step += 1
        return ep


class HashName(PSDispatcher):
    def dispatch(self, varlist):
        return [self.dispatch_one(v) for v in varlist]

    def dispatch_one(self, var):
        # stable across processes: builtin hash() is seed-randomized for
        # strings, which would send trainer pushes and pulls of the same
        # param to different endpoints in different processes
        import zlib
        name = var.name if hasattr(var, "name") else str(var)
        return self._eps[zlib.crc32(name.encode()) % len(self._eps)]
