"""DistributeTranspiler: multi-node training planner.

Capability parity with the reference transpiler (reference:
python/paddle/fluid/transpiler/distribute_transpiler.py:177 `transpile`,
slice_var_up :124, trainer send/recv injection :248-309,
get_pserver_program :333, distributed lookup table :316,916-940) and the
nccl2 mode (reference: doc/fluid/design/dist_train/dist_train_nccl2.md,
gen_nccl_id_op.cc).

TPU-native redesign (SURVEY.md §5.8): there are no pserver processes and no
send/recv ops — every reference distribution mode maps onto GSPMD sharding
over a (possibly multi-host) device mesh:

  - sync pserver mode / nccl2 mode  -> data parallelism over the 'dp' axis;
    gradient aggregation is an XLA all-reduce over ICI/DCN (the transpiled
    program is UNCHANGED — the mesh + shardings do the work).
  - sliced params on pservers       -> ZeRO-style optimizer-state sharding
    (BuildStrategy.ReduceStrategy.Reduce), XLA emits reduce-scatter.
  - distributed lookup table (P5)   -> large embedding tables sharded over
    'mp' (rows), lookups become collective gathers; sparse grads become
    scatter-adds. This transpiler auto-annotates them.
  - gen_nccl_id bootstrap           -> `paddle_tpu.distributed.init` /
    jax.distributed.initialize over DCN (see distributed.py).
  - async (barrierless) updates     -> no collective analog; a host-side
    parameter-server service is the designated follow-up (reference
    RunAsyncLoop, listen_and_serv_op.cc:195).
"""

from __future__ import annotations

import os
from typing import List, Optional

from ..core import ir
from .ps_dispatcher import RoundRobin


class DistributeTranspilerConfig:
    """reference transpiler config: slice_var_up/min_block_size control how
    params were sliced across pservers; here they control when a parameter is
    sharded rather than replicated."""

    slice_var_up = True
    min_block_size = 8192
    split_method = RoundRobin
    mode = "nccl2"  # every sync mode collapses to collectives on TPU
    # TPU extension: shard embedding tables with >= this many rows
    distributed_lookup_threshold = 100_000


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._trainer_id = 0
        self._trainers = 1
        self._program: Optional[ir.Program] = None

    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None):
        if not sync_mode:
            raise NotImplementedError(
                "async (barrierless) update mode has no XLA-collective analog;"
                " it requires the host parameter-server service (planned) — "
                "use sync_mode=True, which matches reference nccl2/sync-pserver"
                " semantics via GSPMD all-reduce")
        self._trainer_id = trainer_id
        self._trainers = trainers if isinstance(trainers, int) \
            else len(trainers.split(","))
        self._program = program or ir.default_main_program()
        self._pserver_endpoints = [e for e in pservers.split(",") if e]
        self._annotate_distributed_tables()
        return self

    def _annotate_distributed_tables(self):
        """Shard big embeddings over 'mp' rows — the distributed-lookup-table
        replacement (reference :316 prefetch rewrite)."""
        block = self._program.global_block()
        threshold = self.config.distributed_lookup_threshold
        for op in block.ops:
            if op.type != "lookup_table":
                continue
            w = block._find_var_recursive(op.input("W")[0])
            if w is None or not isinstance(w, ir.Parameter):
                continue
            if op.attrs.get("is_distributed") or (
                    w.shape and w.shape[0] >= threshold):
                if not w.sharding:
                    w.sharding = ("mp", None)
        self._program._bump()

    def get_trainer_program(self, wait_port=True) -> ir.Program:
        """The trainer program IS the original program: collectives are
        inserted by GSPMD at compile time, not by op rewriting."""
        return self._program

    def get_pserver_program(self, endpoint) -> ir.Program:
        raise NotImplementedError(
            "TPU deployment has no parameter-server processes: parameters "
            "live sharded/replicated in chip HBM and updates run inside the "
            "compiled step. Launch every host with the same trainer program "
            "(see paddle_tpu.distributed.init) — reference "
            "get_pserver_program has no analog")

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint)

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None) -> ir.Program:
        return startup_program or ir.default_startup_program()

    # convenience mirroring reference env-driven setup (trainer.py:321)
    @classmethod
    def from_env(cls):
        t = cls()
        t.transpile(
            trainer_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")),
            trainers=int(os.environ.get("PADDLE_TRAINERS", "1")),
        )
        return t
