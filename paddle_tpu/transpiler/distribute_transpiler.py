"""DistributeTranspiler: multi-node training planner.

Capability parity with the reference transpiler (reference:
python/paddle/fluid/transpiler/distribute_transpiler.py:177 `transpile`,
slice_var_up :124, trainer send/recv injection :248-309,
get_pserver_program :333, distributed lookup table :316,916-940) and the
nccl2 mode (reference: doc/fluid/design/dist_train/dist_train_nccl2.md,
gen_nccl_id_op.cc).

TPU-native redesign (SURVEY.md §5.8): there are no pserver processes and no
send/recv ops — every reference distribution mode maps onto GSPMD sharding
over a (possibly multi-host) device mesh:

  - sync pserver mode / nccl2 mode  -> data parallelism over the 'dp' axis;
    gradient aggregation is an XLA all-reduce over ICI/DCN (the transpiled
    program is UNCHANGED — the mesh + shardings do the work). A
    process-based sync-PS runtime also exists for reference
    execution-mode parity (config.runtime='pserver': per-batch barriers +
    aggregated server-side updates, the RunSyncLoop analog driven by
    pserver.SyncPSTrainer).
  - sliced params on pservers       -> ZeRO-style optimizer-state sharding
    (BuildStrategy.ReduceStrategy.Reduce), XLA emits reduce-scatter.
  - distributed lookup table (P5)   -> large embedding tables sharded over
    'mp' (rows), lookups become collective gathers; sparse grads become
    scatter-adds. This transpiler auto-annotates them.
  - gen_nccl_id bootstrap           -> `paddle_tpu.distributed.init` /
    jax.distributed.initialize over DCN (see distributed.py).
  - async (barrierless) updates     -> no collective analog; a host-side
    parameter-server service is the designated follow-up (reference
    RunAsyncLoop, listen_and_serv_op.cc:195).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..core import ir
from .ps_dispatcher import RoundRobin

# op types that the host pserver can run as its per-param optimize "block"
# (reference get_pserver_program builds one optimize sub-block per param,
# distribute_transpiler.py:333; kernels in paddle_tpu/pserver/optim.py)
OPTIMIZE_OP_TYPES = ("sgd", "momentum", "adam", "adamax", "adagrad",
                     "decayed_adagrad", "adadelta", "rmsprop", "ftrl",
                     "proximal_gd", "proximal_adagrad")


def _verify_split(program: ir.Program, what: str):
    """Static verification of a transpiler output (analysis/verifier):
    these programs are GENERATED — a structural error here is a transpiler
    bug surfacing as a tracer error hours into a distributed run
    otherwise. Structural checks only (no shape sweep): split programs are
    re-verified in full by Executor.prepare when `validate` is on."""
    from ..analysis import (ProgramVerificationError, has_errors,
                            verify_program)
    diags = verify_program(program)
    if has_errors(diags):
        raise ProgramVerificationError(diags, context=what)


class DistributeTranspilerConfig:
    """reference transpiler config: slice_var_up/min_block_size control how
    params were sliced across pservers; here they control when a parameter is
    sharded rather than replicated."""

    slice_var_up = True
    min_block_size = 8192
    split_method = RoundRobin
    mode = "nccl2"  # every sync mode collapses to collectives on TPU
    # sync-mode runtime: "collective" (default — GSPMD all-reduce over the
    # mesh, the TPU-native path) or "pserver" (process-based sync PS with
    # per-batch barriers — the reference RunSyncLoop analog, driven by
    # pserver.SyncPSTrainer; dense params only)
    runtime = "collective"
    # TPU extension: shard embedding tables with >= this many rows
    distributed_lookup_threshold = 100_000
    # static row budget for the per-batch prefetched sub-table (the XLA step
    # needs static shapes; reference prefetch fetched exactly the batch's
    # unique ids — here they are padded to this cap)
    sparse_prefetch_cap = 2048
    # fluid-wire communication compression (EQuARX-grounded, PAPERS.md):
    # None/"raw" keeps full-precision traffic. "int8" / "bf16" quantizes
    # BOTH distribution surfaces this transpiler plans: (a) on the
    # collective (GSPMD) and hybrid dense paths, a comm_quant_dequant op
    # with persistent error feedback is inserted before every optimizer
    # op (wire/graph.py) so each dp shard's gradient contribution is
    # quantized at the all-reduce boundary inside ONE jitted program;
    # (b) on the pserver paths, the trainer's PSClient sends gradient
    # pushes / sparse rows as codec-tagged payloads with client-side
    # error feedback (wire/codec.py; negotiated — legacy servers get raw)
    comm_quant = None
    # fluid-haven replicated PS plane: {primary_endpoint: [backup, ...]}.
    # When set, the PS trainers' client fails over READS AND WRITES to a
    # promoted backup (pushes are seq-tagged so replays dedup
    # server-side), and a primary SIGKILL costs lease-time + one retry
    # budget instead of wedging training. The pair itself is armed on
    # the server side via ParameterServer.start_replication() /
    # start_standby() (docs/FAULT_TOLERANCE.md §Replicated PS plane).
    haven_replicas = None
    # fluid-quorum: the arbiter group backing the haven pairs' elections
    # (a list of node endpoints) + {logical_endpoint: lease resource}.
    # When set, the PS trainers' client asks the ARBITERS who a shard's
    # primary is during failover — it can find a promoted primary at an
    # endpoint no replica list names. Server-side arming stays on
    # ParameterServer.start_replication/start_standby(quorum_endpoints=).
    quorum_endpoints = None
    quorum_resources = None


class DistributeTranspiler:
    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._trainer_id = 0
        self._trainers = 1
        self._program: Optional[ir.Program] = None
        self.sync_mode = True
        self._sync_ps = False
        # async-mode plan, consumed by pserver.AsyncPSTrainer and
        # get_pserver_program
        self.param_specs: Dict[str, dict] = {}   # dense: name -> spec
        self.sparse_specs: Dict[str, dict] = {}  # table name -> spec
        self.grad_names: Dict[str, str] = {}     # param -> grad var name

    def transpile(self, trainer_id, program=None, pservers="", trainers=1,
                  sync_mode=True, startup_program=None, mode=None):
        """mode="hybrid" is the reference's nccl2 + distributed-lookup-table
        composition (P4+P5, the CTR recipe): DENSE parameters keep their
        in-graph optimizer ops — their gradients synchronize through GSPMD
        collectives over the mesh — while distributed lookup tables go to
        the host parameter servers (prefetch + sparse push)."""
        self._trainer_id = trainer_id
        self._trainers = trainers if isinstance(trainers, int) \
            else len(trainers.split(","))
        self._program = program or ir.default_main_program()
        self._pserver_endpoints = [e for e in pservers.split(",") if e]
        self._hybrid = mode == "hybrid"
        self.sync_mode = sync_mode and not self._hybrid
        # process-based sync PS (reference RunSyncLoop): same stripped
        # trainer program and per-param server specs as async — only the
        # trainer driver (SyncPSTrainer: accumulate + barrier-apply)
        # differs
        self._sync_ps = (self.sync_mode
                         and self.config.runtime == "pserver")
        if self._hybrid:
            if not self._pserver_endpoints:
                raise ValueError("hybrid mode needs pservers='host:port,...'")
            self._build_async_plan(dense_local=True)
            # hybrid keeps dense optimizer ops in-graph: their gradients
            # cross the GSPMD all-reduce, so the in-graph quantizer
            # applies to them (the sparse half quantizes on the RPC wire)
            self._apply_comm_quant(startup_program)
        elif self._sync_ps:
            if not self._pserver_endpoints:
                raise ValueError(
                    "sync pserver runtime needs pservers='host:port,...'")
            self._build_async_plan()
        elif sync_mode:
            self._annotate_distributed_tables()
            self._apply_comm_quant(startup_program)
        else:
            if not self._pserver_endpoints:
                raise ValueError("async mode needs pservers='host:port,...'")
            self._build_async_plan()
        return self

    def _apply_comm_quant(self, startup_program=None):
        """fluid-wire in-graph gradient quantization (config.comm_quant)
        for the paths whose gradients cross GSPMD collectives. The
        residual vars zero-init through the startup program, so the usual
        build -> transpile -> run(startup) order materializes them; the
        pserver paths need no program rewrite (the trainer's PSClient
        quantizes on the RPC wire instead)."""
        codec = getattr(self.config, "comm_quant", None)
        if codec in (None, "raw"):
            return
        from ..wire.graph import apply_comm_quant
        apply_comm_quant(
            self._program, codec=codec,
            startup_program=startup_program or ir.default_startup_program())

    # ------------------------------------------------------------------
    # async (barrierless) mode: host parameter-server plan
    # (reference: RunAsyncLoop listen_and_serv_op.cc:195 — per-grad
    # updates, no barriers; trainer send/recv become host-side phases
    # around the jitted step, pserver/client.py)
    # ------------------------------------------------------------------
    def _build_async_plan(self, dense_local=False):
        block = self._program.global_block()
        dispatcher = self.config.split_method(self._pserver_endpoints)

        # 1. distributed lookup tables (their params skip the dense path).
        # No IR rewrite is needed — the executor compiles per feed signature
        # and feeds override scope state, so AsyncPSTrainer feeds the
        # prefetched [cap, width] sub-table under the TABLE'S OWN NAME with
        # batch ids remapped to sub-table rows. Gradients (incl. fan-in sums
        # when a table is looked up twice) then flow to `W@GRAD` with the
        # sub-table's shape automatically. This is the reference's prefetch
        # rewrite (:316) relocated to the host feed boundary.
        sparse_params = set()
        cap = self.config.sparse_prefetch_cap
        for op in block.ops:
            if op.type != "lookup_table" or not op.attrs.get("is_distributed"):
                continue
            wname = op.input("W")[0]
            w = block._find_var_recursive(wname)
            ids_name = op.input("Ids")[0]
            sparse_params.add(wname)
            spec = self.sparse_specs.setdefault(wname, {
                "rows": int(w.shape[0]), "width": int(w.shape[1]),
                "dtype": w.dtype, "cap": cap,
                "ids_names": [], "opt_type": None, "lr_name": None,
                "attrs": {},
            })
            if ids_name not in spec["ids_names"]:
                spec["ids_names"].append(ids_name)

        # 2. find + strip optimizer ops; record per-param server specs.
        # hybrid (dense_local): dense optimizer ops STAY in the program
        # (GSPMD collectives synchronize their grads); only the sparse
        # tables' updates move server-side.
        keep_ops = []
        for op in block.ops:
            if op.type not in OPTIMIZE_OP_TYPES:
                keep_ops.append(op)
                continue
            pname = op.input("Param")[0]
            gname = op.input("Grad")[0]
            lr_name = (op.input("LearningRate") or [None])[0]
            if pname in sparse_params:
                self.grad_names[pname] = gname
                self.sparse_specs[pname].update(
                    opt_type=op.type, lr_name=lr_name, attrs=dict(op.attrs))
                continue  # table updates go through push_sparse_grad
            if dense_local:
                keep_ops.append(op)
                continue
            self.grad_names[pname] = gname
            self.param_specs[pname] = {
                "opt_type": op.type, "lr_name": lr_name,
                "attrs": dict(op.attrs),
                "endpoint": dispatcher.dispatch_one(pname),
            }
        block.ops[:] = keep_ops
        self._program._bump()

        for wname, spec in self.sparse_specs.items():
            if spec["opt_type"] is None:
                raise ValueError(
                    f"distributed table {wname!r} has no optimizer op — call "
                    f"optimizer.minimize before transpile (reference order)")

    def _annotate_distributed_tables(self):
        """Shard big embeddings over 'mp' rows — the distributed-lookup-table
        replacement (reference :316 prefetch rewrite)."""
        block = self._program.global_block()
        threshold = self.config.distributed_lookup_threshold
        for op in block.ops:
            if op.type != "lookup_table":
                continue
            w = block._find_var_recursive(op.input("W")[0])
            if w is None or not isinstance(w, ir.Parameter):
                continue
            if op.attrs.get("is_distributed") or (
                    w.shape and w.shape[0] >= threshold):
                if not w.sharding:
                    w.sharding = ("mp", None)
        self._program._bump()

    def get_trainer_program(self, wait_port=True) -> ir.Program:
        """Sync mode: the trainer program IS the original program —
        collectives are inserted by GSPMD at compile time. Async mode: the
        program with optimizer ops stripped (updates run on the pservers);
        drive it with pserver.AsyncPSTrainer, which adds the host-side
        pull/push phases the reference expressed as send/recv ops."""
        _verify_split(self._program, "trainer program")
        return self._program

    def get_pserver_program(self, endpoint) -> ir.Program:
        """A program holding one `listen_and_serv` op (reference
        listen_and_serv_op.cc); `Executor.run` on it blocks serving.
        Available in async mode, hybrid mode, and — since round 5 — the
        sync "pserver" runtime (RunSyncLoop analog: per-batch barriers,
        aggregated server-side updates). The sync DEFAULT on TPU remains
        the collective runtime: parameters live sharded/replicated in
        chip HBM and updates run inside the compiled step (GSPMD
        all-reduce) — set DistributeTranspilerConfig.runtime='pserver'
        for the process-based mode."""
        if self.sync_mode and not self._sync_ps:
            raise NotImplementedError(
                "sync mode with runtime='collective' has no parameter-"
                "server processes: GSPMD owns the exchange. Set "
                "DistributeTranspilerConfig.runtime='pserver' for the "
                "process-based sync runtime (RunSyncLoop analog), or "
                "sync_mode=False for async")
        prog = ir.Program()
        # the server is generic: params/tables arrive via init_param /
        # init_table RPCs from the trainers (first writer wins), so the op
        # carries only what the service loop consumes
        prog.global_block().append_op(
            "listen_and_serv",
            attrs={"endpoint": endpoint, "trainers": self._trainers})
        _verify_split(prog, f"pserver program for {endpoint}")
        return prog

    def get_pserver_programs(self, endpoint):
        return self.get_pserver_program(endpoint)

    def get_startup_program(self, endpoint=None, pserver_program=None,
                            startup_program=None) -> ir.Program:
        return startup_program or ir.default_startup_program()

    # convenience mirroring reference env-driven setup (trainer.py:321)
    @classmethod
    def from_env(cls):
        t = cls()
        t.transpile(
            trainer_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")),
            trainers=int(os.environ.get("PADDLE_TRAINERS", "1")),
        )
        return t
