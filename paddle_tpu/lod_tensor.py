"""LoD tensor construction helpers.

Capability parity with the reference's lod_tensor module (reference:
python/paddle/fluid/lod_tensor.py — create_lod_tensor :21,
create_random_int_lodtensor :90). The reference packs ragged data into a
flat [sum_T, ...] buffer plus offset tables; the TPU representation is a
PADDED dense array plus per-level length companions — the pair these
helpers return feeds straight into `exe.run(feed={name: pair})`.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def create_lod_tensor(data, recursive_seq_lens: Sequence[Sequence[int]],
                      place=None):
    """Build the padded feed pair from ragged data.

    data: flat array [sum_T, feat...] (reference layout) or a nested
    python list. recursive_seq_lens: one list of lengths per LoD level,
    outermost first — e.g. [[2, 1], [3, 2, 4]] means 2 samples, the first
    holding sequences of 3 and 2 tokens, the second one of 4.

    Returns: (padded, lengths) for 1 level, or
             (padded, (outer_counts, inner_lengths)) for 2 levels.
    """
    levels = [list(l) for l in recursive_seq_lens]
    if not levels or len(levels) > 2:
        raise ValueError("recursive_seq_lens must have 1 or 2 levels")
    total = int(np.sum(levels[-1]))
    if isinstance(data, list):
        # accept the reference's nested python-list form: flatten outer
        # list levels (by token count, so rectangular nesting cannot be
        # misread as a pre-flattened feature matrix) until one row per
        # token remains
        while (len(data) != total and data
               and isinstance(data[0], (list, tuple))):
            data = [x for sub in data for x in sub]
        if len(data) != total:
            raise ValueError(
                f"data holds {len(data)} tokens but recursive_seq_lens "
                f"sums to {total}")
    arr = np.asarray(data)
    if len(levels) == 1:
        lens = np.asarray(levels[0], np.int32)
        feat = list(arr.shape[1:])
        T = max(1, int(lens.max()))
        padded = np.zeros([len(lens), T] + feat, arr.dtype)
        off = 0
        for b, L in enumerate(lens):
            padded[b, :L] = arr[off:off + L]
            off += L
        return padded, lens

    outer = np.asarray(levels[0], np.int32)           # sequences per sample
    flat_inner = list(levels[1])                      # tokens per sequence
    if len(flat_inner) != int(outer.sum()):
        raise ValueError(
            f"level-1 has {len(flat_inner)} entries but level-0 sums to "
            f"{int(outer.sum())}")
    B = len(outer)
    S = max(1, int(outer.max()))
    inner = np.zeros((B, S), np.int32)
    k = 0
    for b, n in enumerate(outer):
        for s_i in range(n):
            inner[b, s_i] = flat_inner[k]
            k += 1
    T = max(1, int(inner.max()))
    feat = list(arr.shape[1:])
    padded = np.zeros([B, S, T] + feat, arr.dtype)
    off = 0
    for b in range(B):
        for s_i in range(int(outer[b])):
            L = int(inner[b, s_i])
            padded[b, s_i, :L] = arr[off:off + L]
            off += L
    return padded, (outer, inner)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1):
    """reference lod_tensor.py:90: random ints under the given LoD."""
    total = int(np.sum(recursive_seq_lens[-1]))
    data = np.random.randint(low, high + 1,
                             [total] + list(base_shape)).astype(np.int64)
    return create_lod_tensor(data, recursive_seq_lens, place)


def lod_to_list(padded, lens) -> List:
    """Inverse of create_lod_tensor: recover the ragged python lists."""
    if isinstance(lens, tuple):
        outer, inner = lens
        return [[padded[b, s, : int(inner[b, s])].tolist()
                 for s in range(int(outer[b]))]
                for b in range(len(outer))]
    return [padded[b, : int(L)].tolist() for b, L in enumerate(lens)]
