"""Draw a Program's op graph as graphviz dot (reference
python/paddle/fluid/net_drawer.py draw_graph/parse_graph). Walks the IR
directly instead of the reference's protobuf-to-json round trip."""

from __future__ import annotations

import argparse
import logging

from .core import ir
from .graphviz import Graph

logger = logging.getLogger(__name__)

OP_STYLE = {"shape": "ellipse", "style": "filled", "fillcolor": "lightblue"}
VAR_STYLE = {"shape": "box", "style": "rounded"}

def parse_graph(program, graph, var_dict):
    """Append `program`'s global-block ops + data-flow edges to `graph`."""
    for op in program.global_block().ops:
        op_node = graph.node(op.type, prefix="op", **OP_STYLE)
        for slot, names in op.inputs.items():
            for name in names:
                if name not in var_dict:
                    var_dict[name] = graph.node(name, prefix="var",
                                                **VAR_STYLE)
                graph.edge(var_dict[name], op_node, label=slot)
        for slot, names in op.outputs.items():
            for name in names:
                if name not in var_dict:
                    var_dict[name] = graph.node(name, prefix="var",
                                                **VAR_STYLE)
                graph.edge(op_node, var_dict[name], label=slot)
    return graph


def draw_graph(startup_program, main_program, **kwargs):
    """Render both programs into one dot graph; returns the Graph (and
    writes `filename` when given — reference draw_graph contract).
    `graph_attr` dict entries become dot graph attributes."""
    graph_attr = dict(kwargs.pop("graph_attr", {}) or {})
    filename = kwargs.pop("filename", None) or graph_attr.pop("filename",
                                                              None)
    graph_attr.setdefault("rankdir", "TB")
    graph = Graph("ProgramDesc", **graph_attr)
    var_dict = {}
    parse_graph(startup_program, graph, var_dict)
    parse_graph(main_program, graph, var_dict)
    if filename:
        graph.compile(filename)
    return graph


def main():
    parser = argparse.ArgumentParser(description="draw the default program")
    parser.add_argument("--output", default="program.dot")
    args = parser.parse_args()
    g = draw_graph(ir.default_startup_program(), ir.default_main_program())
    g.compile(args.output)
    logger.info("wrote %s", args.output)


if __name__ == "__main__":
    main()
