"""Reader -> RecordIO conversion (reference:
python/paddle/fluid/recordio_writer.py — convert_reader_to_recordio_file
serialized each batch through a DataFeeder into a RecordIO record).

Record format: one pickled tuple of numpy arrays per sample, the layout
`layers.open_recordio_file` / `layers.open_files` scan back (they batch
records and feed the py_reader queue)."""

from __future__ import annotations

import pickle

import numpy as np

from . import recordio

__all__ = [
    "convert_reader_to_recordio_file", "convert_reader_to_recordio_files"
]


def convert_reader_to_recordio_file(filename, reader_creator, feeder=None,
                                    compressor=None, max_num_records=1000,
                                    feed_order=None):
    """Write every sample from `reader_creator()` into one RecordIO file;
    returns the record count (reference recordio_writer.py:24)."""
    kw = {}
    if compressor is not None:
        kw["compressor"] = compressor
    n = 0
    with recordio.Writer(filename, **kw) as w:
        for sample in reader_creator():
            arrays = tuple(np.asarray(f) for f in sample)
            w.write(pickle.dumps(arrays))
            n += 1
    return n


def convert_reader_to_recordio_files(filename, batch_per_file,
                                     reader_creator, feeder=None,
                                     compressor=None, max_num_records=1000,
                                     feed_order=None):
    """Shard the reader across `.part-N` files, `batch_per_file` records
    each (reference recordio_writer.py:46)."""
    lines = list(reader_creator())
    counts = []
    for i in range(0, len(lines), batch_per_file):
        part = f"{filename}-{i // batch_per_file:05d}"
        counts.append(convert_reader_to_recordio_file(
            part, lambda chunk=lines[i:i + batch_per_file]: iter(chunk),
            feeder, compressor))
    return counts
