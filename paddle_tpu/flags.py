"""Runtime flag registry (reference: gflags end-to-end — FLAGS_check_nan_inf
/ FLAGS_benchmark etc. in C++, forwarded from `FLAGS_*` environment
variables at import by python/paddle/fluid/__init__.py; SURVEY.md §5.6).

Flags initialize from `PADDLE_TPU_<NAME>` (or legacy `FLAGS_<name>`)
environment variables and can be flipped at runtime with `set_flag`:
executors read the registry at run time (the flag value is part of the
compile-cache key), so a flip takes effect on the next `run` call."""

from __future__ import annotations

import os
from typing import Any, Dict

_DEFS: Dict[str, tuple] = {
    # name: (default, type)
    "check_nan_inf": (False, bool),   # reference FLAGS_check_nan_inf
    "benchmark": (False, bool),       # reference FLAGS_benchmark
    "profile": (False, bool),
    # dropout lowering: "auto"/"xla" = the fused counter-hash XLA path
    # (measured default, docs/PERF.md); "pallas" forces the in-kernel-PRNG
    # Pallas kernel on eligible tensors for A/B measurement
    "dropout_impl": ("auto", str),
    # XLA compile options for the jitted step (round-5 flag sweep,
    # docs/PERF.md): "auto" = the measured-good TPU set (scoped VMEM
    # 32 MiB — bigger fusion budget, worth ~9% on transformer-base);
    # "" / "none" = compiler defaults; or an explicit comma-separated
    # k=v list (e.g. "xla_tpu_scoped_vmem_limit_kib=65536")
    "xla_compiler_options": ("auto", str),
    # static program verification on Executor.prepare()/run() (analysis/):
    # "error" rejects malformed programs before any XLA lowering, "warn"
    # logs the diagnostics and proceeds, "off" (default) skips the sweep
    "validate": ("off", str),
    # runtime telemetry (observe/): per-step phase timings, feeder queue
    # gauges, pserver RPC counters, recompile-cause metrics. Off (default)
    # keeps the prepared fast path free of registry writes; compile-time
    # recompile events are recorded regardless (they are never hot)
    "observe": (False, bool),
    # the distributed-tracing half of the observe plane (observe/xray):
    # span ids, span recording, and the traceparent element on outbound
    # RPC frames. Only consulted while "observe" is on; turning it off
    # leaves metrics/pulse armed but makes every wire frame legacy-shaped
    # and every span a no-op — bench.py's horizon segment A/Bs exactly
    # this bit to price trace context on the serve path
    "trace": (True, bool),
}

_FLAGS: Dict[str, Any] = {}

# bumped on every set_flag: executors key their prepared-program memo on
# this, turning the per-step "did any flag change?" check into one int
# compare instead of N registry reads (the flag registry stays the source
# of truth — a flip still takes effect on the next run call)
_VERSION = 0


def version() -> int:
    return _VERSION


def _coerce(val: str, typ):
    if typ is bool:
        return val.lower() in ("1", "true", "yes", "on")
    return typ(val)


def _init():
    for name, (default, typ) in _DEFS.items():
        env = os.environ.get(f"PADDLE_TPU_{name.upper()}",
                             os.environ.get(f"FLAGS_{name}"))
        val = _coerce(env, typ) if env is not None else default
        if name in _CHOICES and env is not None:
            val = str(val).lower()
            if val not in _CHOICES[name]:
                raise ValueError(f"flag {name!r} must be one of "
                                 f"{_CHOICES[name]}, got {val!r}")
        _FLAGS[name] = val


def get_flag(name: str):
    if name not in _FLAGS:
        raise KeyError(f"unknown flag {name!r}; known: {sorted(_FLAGS)}")
    return _FLAGS[name]


# enumerated string flags: value must be one of the choices (a typo like
# dropout_impl=palas would otherwise silently select the default path)
_CHOICES: Dict[str, tuple] = {
    "dropout_impl": ("auto", "pallas", "xla"),
    "validate": ("error", "warn", "off"),
}


def set_flag(name: str, value):
    global _VERSION
    if name not in _FLAGS:
        raise KeyError(f"unknown flag {name!r}; known: {sorted(_FLAGS)}")
    if name in _CHOICES:
        value = str(value).lower()
        if value not in _CHOICES[name]:
            raise ValueError(
                f"flag {name!r} must be one of {_CHOICES[name]}, got {value!r}")
    _FLAGS[name] = value
    _VERSION += 1


def all_flags() -> Dict[str, Any]:
    return dict(_FLAGS)


_init()
