"""Whole-program static shape/dtype propagation.

Capability parity with the reference's compile-time InferShape sweep
(reference: framework/shape_inference.h:30 — every OpDesc's InferShape
runs against the BlockDesc before execution; SURVEY §2 "Shape
inference"). TPU-native redesign: there are no per-op InferShape
methods — `registry.infer_op_shapes` derives each op's output shapes
from its JAX lowering rule via `jax.eval_shape`, so the rule stays the
single source of truth. This module threads that per-op inference
through a WHOLE program: op by op, block by block (control-flow
sub-blocks see the enclosing env), carrying -1 batch dims, and
cross-checking every declared `Variable.shape/dtype` against what the
rules actually produce. A mismatch at build time here is a tracer error
with no provenance at step-compile time otherwise.

Generic grad ops don't re-trace under eval_shape: a gradient has its
base variable's shape by construction (`x@GRAD[@RENAME@k]` takes the
shape of `x`), which is also how the reference's grad-op InferShape
worked (SetOutputDim(GradVarName(x), GetInputDim(x)))."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..core import ir, registry, types
from ..core.registry import EMPTY_VAR, FWD_OP_ATTR, GRAD_OP_SUFFIX
from .diagnostics import Diagnostic, Severity, diag_for_op
from .verifier import PSEUDO_OPS

ShapeDtype = Tuple[Tuple[int, ...], str]


def infer_program_shapes(program: ir.Program, update: bool = False,
                         ) -> Tuple[Dict[str, ShapeDtype], List[Diagnostic]]:
    """Propagate shapes through the whole program.

    Returns ({var name -> (shape, dtype)}, diagnostics). With `update`,
    inferred results are written back onto Variables whose declared shape
    was empty (the build-time-inference-failed gap); declared non-empty
    shapes are never rewritten — they are the user's contract and
    mismatches are reported instead.
    """
    diags: List[Diagnostic] = []
    env: Dict[str, ShapeDtype] = {}
    _seed_env(program, env)
    _infer_block(program, program.global_block(), env, diags, update,
                 visited=set())
    return env, diags


def check_program_shapes(program: ir.Program) -> List[Diagnostic]:
    """Cross-check only (no write-back)."""
    return infer_program_shapes(program, update=False)[1]


def _seed_env(program: ir.Program, env: Dict[str, ShapeDtype]):
    """Roots of propagation: vars whose values exist before any op runs —
    fed data (with @SEQLEN companions) and persistables. Temporaries are
    NOT seeded: their declared shapes are re-derived and cross-checked."""
    for blk in program.blocks:
        for v in blk.vars.values():
            if (v.is_data or v.persistable) and v.shape != ():
                env[v.name] = (tuple(v.shape), v.dtype)
                for lvl in range(v.lod_level):
                    env.setdefault(ir.seqlen_var_name(v.name, lvl),
                                   ((-1,) * (lvl + 1), "int32"))


def _lookup(program, block, name, env) -> Optional[ShapeDtype]:
    if name in env:
        return env[name]
    v = block._find_var_recursive(name)
    if v is not None and v.shape != ():
        return (tuple(v.shape), v.dtype)
    return None


def _infer_block(program, block, env, diags, update, visited):
    visited.add(block.idx)
    for op_idx, op in enumerate(block.ops):
        if op.type in PSEUDO_OPS:
            continue
        if op.type.endswith(GRAD_OP_SUFFIX) and FWD_OP_ATTR in op.attrs:
            _infer_grad_op(program, block, op, env)
            continue
        sub_idxs = ir.sub_block_indices(op)
        if sub_idxs:
            # control-flow: infer through the body with the enclosing env
            # (this is where -1 batch dims thread block-by-block), then
            # take the op's own outputs from their declarations — the
            # carry/stack plumbing is the lowering rule's business.
            for si in sub_idxs:
                if si < len(program.blocks) and si not in visited:
                    _infer_block(program, program.blocks[si], env, diags,
                                 update, visited)
            _fallback_outputs(program, block, op, env)
            continue
        if not registry.is_registered(op.type):
            continue  # verifier already reported unknown-op

        ins_by_slot, unknown = {}, None
        for slot, names in op.inputs.items():
            pairs = []
            for n in names:
                if n == EMPTY_VAR:
                    continue
                sd = _lookup(program, block, n, env)
                if sd is None:
                    unknown = n
                    break
                pairs.append(sd)
            if unknown:
                break
            ins_by_slot[slot] = pairs
        if unknown:
            diags.append(diag_for_op(
                "shape-infer-skip", Severity.INFO,
                f"cannot infer: input {unknown!r} has no known shape",
                block, op_idx, op, var=unknown))
            _fallback_outputs(program, block, op, env)
            continue

        try:
            result = registry.infer_op_shapes(op.type, op.attrs, ins_by_slot)
        except Exception as e:  # rule refused the abstract trace
            diags.append(diag_for_op(
                "shape-infer-skip", Severity.INFO,
                f"abstract eval failed: {type(e).__name__}: {e}",
                block, op_idx, op))
            _fallback_outputs(program, block, op, env)
            continue

        for slot, names in op.outputs.items():
            inferred = result.get(slot)
            if inferred is None:
                continue
            for n, (shape, dtype) in zip(names, inferred):
                if n == EMPTY_VAR:
                    continue
                _check_against_declared(program, block, op, op_idx, n,
                                        shape, dtype, diags, update)
                env[n] = (tuple(shape), dtype)


def _infer_grad_op(program, block, op, env):
    for n in op.output_arg_names:
        if n == EMPTY_VAR or ir.GRAD_SUFFIX not in n:
            continue
        base = n.split(ir.GRAD_SUFFIX)[0]
        sd = _lookup(program, block, base, env)
        if sd is not None:
            env[n] = sd


def _fallback_outputs(program, block, op, env):
    """Outputs whose shapes inference can't derive keep their declared
    shapes (runtime stays authoritative), so downstream ops still infer."""
    for n in op.output_arg_names:
        if n == EMPTY_VAR or n in env:
            continue
        v = block._find_var_recursive(n)
        if v is not None and v.shape != ():
            env[n] = (tuple(v.shape), v.dtype)


def _dims_compatible(declared: Sequence[int], inferred: Sequence[int]) -> bool:
    if len(declared) != len(inferred):
        return False
    return all(d == -1 or i == -1 or int(d) == int(i)
               for d, i in zip(declared, inferred))


def _check_against_declared(program, block, op, op_idx, name, shape, dtype,
                            diags, update):
    v = block._find_var_recursive(name)
    if v is None:
        return
    if v.shape == ():
        if update:  # fill the build-time-inference gap
            v.shape = tuple(int(d) for d in shape)
            v.dtype = types.canonical_dtype(dtype)
        return
    if not _dims_compatible(v.shape, shape):
        diags.append(diag_for_op(
            "shape-mismatch", Severity.ERROR,
            f"output {name!r} is declared {tuple(v.shape)} but the "
            f"lowering rule produces {tuple(shape)} — the declaration "
            f"(and everything built downstream of it) is wrong",
            block, op_idx, op, var=name))
        return
    if types.canonical_dtype(v.dtype) != types.canonical_dtype(dtype):
        diags.append(diag_for_op(
            "dtype-mismatch", Severity.ERROR,
            f"output {name!r} is declared {v.dtype} but the lowering rule "
            f"produces {dtype}", block, op_idx, op, var=name))
