"""Per-op cost attribution over the Program IR (fluid-xray, part 2).

GDP-style placement learners, the auto-sharding planner (ROADMAP item 4)
and plain capacity planning all want the same table: for every op of the
dataflow graph, how many FLOPs it computes, how many bytes it moves, and
how much memory its output occupies. The runtime can only report
aggregate step time; this module derives the per-op breakdown
*statically*, by propagating concrete shapes through the program with
the same `registry.infer_op_shapes` machinery the shape verifier uses,
then applying per-op-type arithmetic-intensity rules.

Honesty contract: the FLOP counts follow XLA's own convention (a dot of
[M,K]x[K,N] is 2·M·K·N; elementwise ops are one FLOP per output element;
transcendentals are NOT counted as FLOPs — XLA tallies them separately),
so the program total can be cross-checked against
`jax.jit(...).lower(...).compile().cost_analysis()["flops"]` — the test
suite pins agreement within 10% on the book transformer, and
`tools/op_profile.py --xla-check` reports the live ratio for any model.

Known approximations:
- ops inside control-flow sub-blocks are counted ONCE (not x trip
  count) — the bounded `while` trip count is a runtime value;
- gradient ops of matmul-like ops are costed from their forward
  counterpart (one full product per produced input-grad), the standard
  2x-forward rule;
- `-1` dims with no feed to resolve them fall back to `default_dim`.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import ir, registry
from ..core.registry import EMPTY_VAR, GRAD_OP_SUFFIX
from .verifier import PSEUDO_OPS

ShapeDtype = Tuple[Tuple[int, ...], str]

# op families whose cost is a dense product (2*M*K*N-style)
_MATMUL_LIKE = ("mul", "matmul", "conv2d", "depthwise_conv2d")

# elementwise-ish FLOPs per OUTPUT element, by op type. XLA convention:
# exp/log/tanh/rsqrt are transcendentals, not flops, so e.g. softmax is
# (sub max, sum, div) ~ 3 non-transcendental flops/elem.
_ELEM_FLOPS = {
    "relu": 1.0, "relu6": 1.0, "leaky_relu": 2.0, "sigmoid": 2.0,
    "tanh": 1.0, "gelu": 6.0, "scale": 1.0, "dropout": 2.0, "cast": 0.0,
    "elementwise_add": 1.0, "elementwise_sub": 1.0, "elementwise_mul": 1.0,
    "elementwise_div": 1.0, "elementwise_max": 1.0, "elementwise_min": 1.0,
    "elementwise_pow": 1.0, "sum": 1.0, "sqrt": 0.0, "square": 1.0,
    "softmax": 3.0, "log_softmax": 3.0,
    "layer_norm": 7.0, "batch_norm": 5.0,
    "softmax_with_cross_entropy": 4.0, "cross_entropy": 1.0,
    "sgd": 2.0, "momentum": 4.0, "adam": 10.0, "adagrad": 5.0,
    "clip": 1.0, "abs": 1.0, "pow": 1.0,
}

# grad-op elementwise factors where the backward is notably denser than
# one flop/elem (defaults to the forward factor, then to 1.0)
_GRAD_ELEM_FLOPS = {
    "softmax": 4.0, "layer_norm": 8.0, "batch_norm": 6.0, "dropout": 1.0,
    "softmax_with_cross_entropy": 2.0, "mean": 1.0, "gelu": 8.0,
}

# pure data-movement ops: zero FLOPs, bytes still counted
_MOVEMENT = {
    "reshape", "transpose", "concat", "stack", "split", "slice",
    "squeeze", "unsqueeze", "fill_constant", "fill_zeros_like",
    "assign", "shape", "lookup_table", "gather", "scatter",
    "expand", "pad", "sequence_pad", "sequence_unpad", "one_hot",
    "causal_mask", "sinusoid_pos_encoding", "uniform_random",
    "gaussian_random", "range", "arange", "flatten",
    "space_to_depth", "pixel_shuffle",
}

_DTYPE_BYTES = {"float64": 8, "int64": 8, "float32": 4, "int32": 4,
                "float16": 2, "bfloat16": 2, "int16": 2, "int8": 1,
                "uint8": 1, "bool": 1}


def _nbytes(sd: Optional[ShapeDtype]) -> float:
    if sd is None:
        return 0.0
    shape, dtype = sd
    return float(np.prod([max(int(d), 1) for d in shape])
                 if shape else 1) * _DTYPE_BYTES.get(str(dtype), 4)


def _nelems(shape: Sequence[int]) -> float:
    return float(np.prod([max(int(d), 1) for d in shape])) if shape else 1.0


class OpCost:
    """One op's static cost estimate."""

    __slots__ = ("block_idx", "op_idx", "op_type", "out_name", "flops",
                 "bytes", "out_bytes")

    def __init__(self, block_idx, op_idx, op_type, out_name, flops,
                 bytes_, out_bytes):
        self.block_idx = block_idx
        self.op_idx = op_idx
        self.op_type = op_type
        self.out_name = out_name
        self.flops = float(flops)
        self.bytes = float(bytes_)       # input + output traffic
        self.out_bytes = float(out_bytes)  # est. memory its outputs occupy

    def as_dict(self) -> dict:
        return {"block": self.block_idx, "op": self.op_idx,
                "type": self.op_type, "out": self.out_name,
                "flops": self.flops, "bytes": self.bytes,
                "out_bytes": self.out_bytes}

    def __repr__(self):
        return (f"OpCost({self.op_type}:{self.out_name}, "
                f"flops={self.flops:.3g}, bytes={self.bytes:.3g})")


class CostReport:
    """Whole-program cost table + aggregates."""

    def __init__(self, ops: List[OpCost], param_bytes: float,
                 unresolved: List[str]):
        self.ops = ops
        self.param_bytes = float(param_bytes)
        # ops whose shapes could not be derived (costed by fallback)
        self.unresolved = unresolved

    @property
    def total_flops(self) -> float:
        return sum(o.flops for o in self.ops)

    @property
    def total_bytes(self) -> float:
        return sum(o.bytes for o in self.ops)

    @property
    def total_out_bytes(self) -> float:
        return sum(o.out_bytes for o in self.ops)

    def by_type(self) -> Dict[str, dict]:
        agg: Dict[str, dict] = {}
        for o in self.ops:
            a = agg.setdefault(o.op_type, {"count": 0, "flops": 0.0,
                                           "bytes": 0.0, "out_bytes": 0.0})
            a["count"] += 1
            a["flops"] += o.flops
            a["bytes"] += o.bytes
            a["out_bytes"] += o.out_bytes
        return agg

    def top(self, k: int = 10, key: str = "flops") -> List[OpCost]:
        return sorted(self.ops, key=lambda o: -getattr(o, key))[:k]

    def as_dict(self, top_k: int = 10) -> dict:
        total = self.total_flops or 1.0
        return {
            "total_flops": self.total_flops,
            "total_bytes": self.total_bytes,
            "total_out_bytes": self.total_out_bytes,
            "param_bytes": self.param_bytes,
            "arithmetic_intensity": (self.total_flops
                                     / max(self.total_bytes, 1.0)),
            "ops": len(self.ops),
            "unresolved": len(self.unresolved),
            "by_type": {t: dict(a, flops_share=round(a["flops"] / total, 4))
                        for t, a in sorted(self.by_type().items(),
                                           key=lambda kv: -kv[1]["flops"])},
            "top": [dict(o.as_dict(),
                         flops_share=round(o.flops / total, 4))
                    for o in self.top(top_k)],
        }

    def table(self, k: int = 15, step_time_s: Optional[float] = None) -> str:
        """Human top-k table; with `step_time_s` (measured device_compute
        from StepStats) each op also gets its est. time share."""
        total = self.total_flops or 1.0
        lines = [f"{'op':<28} {'type':<22} {'GFLOPs':>10} {'MB':>9} "
                 f"{'share':>7}" + ("  est_time" if step_time_s else "")]
        for o in self.top(k):
            share = o.flops / total
            line = (f"{o.out_name[:28]:<28} {o.op_type[:22]:<22} "
                    f"{o.flops / 1e9:>10.4f} {o.bytes / 1e6:>9.2f} "
                    f"{share:>6.1%}")
            if step_time_s:
                line += f"  {share * step_time_s * 1e3:8.3f} ms"
            lines.append(line)
        lines.append(
            f"TOTAL: {self.total_flops / 1e9:.3f} GFLOPs, "
            f"{self.total_bytes / 1e6:.1f} MB moved, "
            f"params {self.param_bytes / 1e6:.1f} MB, "
            f"AI {self.total_flops / max(self.total_bytes, 1.0):.1f} "
            f"flops/byte")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# concrete shape propagation
# ---------------------------------------------------------------------------

def _resolve(shape, default_dim: int) -> Tuple[int, ...]:
    return tuple(int(d) if int(d) != -1 else int(default_dim)
                 for d in shape)


def _seed_env(program, env, feed_shapes, default_dim):
    for blk in program.blocks:
        for v in blk.vars.values():
            if v.is_data and v.lod_level > 0:
                # @SEQLEN companions: one int32 length per sequence
                # level — seeded for FED LoD vars too (the feed gives
                # the batch extent; the fed var itself is already in env)
                batch = (feed_shapes.get(v.name, v.shape) or (default_dim,))
                b = int(batch[0]) if int(batch[0]) != -1 else default_dim
                for lvl in range(v.lod_level):
                    env.setdefault(ir.seqlen_var_name(v.name, lvl),
                                   ((b,) * (lvl + 1), "int32"))
            if v.name in feed_shapes:
                continue
            if (v.persistable or v.is_data) and v.shape != ():
                env[v.name] = (_resolve(v.shape, default_dim), v.dtype)


def _concrete_env(program, feed_shapes: Dict[str, Sequence[int]],
                  default_dim: int, unresolved: List[str]
                  ) -> Dict[str, ShapeDtype]:
    """Propagate CONCRETE shapes (no -1 anywhere) through the program.
    Feeds seed the batch dims; every other var follows from the lowering
    rules; declared shapes (with -1 -> default_dim) are the fallback."""
    env: Dict[str, ShapeDtype] = {}
    blk0 = program.global_block()
    for name, shape in feed_shapes.items():
        v = blk0._find_var_recursive(name)
        dtype = v.dtype if v is not None and v.dtype else "float32"
        env[name] = (tuple(int(d) for d in shape), dtype)
    _seed_env(program, env, feed_shapes, default_dim)
    visited: set = set()
    _walk_block(program, blk0, env, default_dim, unresolved, visited)
    return env


def _fallback_outputs(block, op, env, default_dim, unresolved):
    for n in op.output_arg_names:
        if n == EMPTY_VAR or n in env:
            continue
        v = block._find_var_recursive(n)
        if v is not None and v.shape != ():
            env[n] = (_resolve(v.shape, default_dim), v.dtype)
        else:
            unresolved.append(n)


def _walk_block(program, block, env, default_dim, unresolved, visited):
    visited.add(block.idx)
    for op in block.ops:
        if op.type in PSEUDO_OPS:
            continue
        if op.type.endswith(GRAD_OP_SUFFIX):
            # a grad has its base variable's shape by construction
            for n in op.output_arg_names:
                if n == EMPTY_VAR or ir.GRAD_SUFFIX not in n:
                    continue
                base = n.split(ir.GRAD_SUFFIX)[0]
                if base in env:
                    env[n] = env[base]
                else:
                    _fallback_outputs(block, op, env, default_dim,
                                      unresolved)
            continue
        subs = ir.sub_block_indices(op)
        if subs:
            for si in subs:
                if si < len(program.blocks) and si not in visited:
                    _walk_block(program, program.blocks[si], env,
                                default_dim, unresolved, visited)
            _fallback_outputs(block, op, env, default_dim, unresolved)
            continue
        if not registry.is_registered(op.type):
            _fallback_outputs(block, op, env, default_dim, unresolved)
            continue
        ins_by_slot, missing = {}, False
        for slot, names in op.inputs.items():
            pairs = []
            for n in names:
                if n == EMPTY_VAR:
                    continue
                sd = env.get(n)
                if sd is None:
                    v = block._find_var_recursive(n)
                    if v is not None and v.shape != ():
                        sd = (_resolve(v.shape, default_dim), v.dtype)
                    else:
                        missing = True
                        break
                pairs.append(sd)
            if missing:
                break
            ins_by_slot[slot] = pairs
        if missing:
            _fallback_outputs(block, op, env, default_dim, unresolved)
            continue
        try:
            result = registry.infer_op_shapes(op.type, op.attrs, ins_by_slot)
        except Exception:
            _fallback_outputs(block, op, env, default_dim, unresolved)
            continue
        for slot, names in op.outputs.items():
            inferred = result.get(slot)
            if inferred is None:
                continue
            for n, (shape, dtype) in zip(names, inferred):
                if n != EMPTY_VAR:
                    env[n] = (_resolve(shape, default_dim), dtype)
        _fallback_outputs(block, op, env, default_dim, unresolved)


# ---------------------------------------------------------------------------
# per-op FLOP rules
# ---------------------------------------------------------------------------

def _shape_of(env, block, name, default_dim) -> Optional[Tuple[int, ...]]:
    sd = env.get(name)
    if sd is not None:
        return sd[0]
    v = block._find_var_recursive(name)
    if v is not None and v.shape != ():
        return _resolve(v.shape, default_dim)
    return None


def _first(op, slot):
    names = op.inputs.get(slot) or ()
    return names[0] if names and names[0] != EMPTY_VAR else None


def _matmul_flops(op, env, block, default_dim) -> float:
    """2*M*K*N for mul/matmul; 2*out_elems*(kh*kw*cin/groups) for conv."""
    out = op.output_arg_names[0]
    out_shape = _shape_of(env, block, out, default_dim)
    if out_shape is None:
        return 0.0
    if op.type in ("conv2d", "depthwise_conv2d"):
        w = _first(op, "Filter") or _first(op, "W")
        w_shape = _shape_of(env, block, w, default_dim) if w else None
        if w_shape is None or len(w_shape) < 4:
            return 2.0 * _nelems(out_shape)
        # The per-output-element multiply count is the filter volume
        # without its Cout axis (grouping is already folded into the
        # filter's Cin/g extent). `data_format` describes the DATA
        # layout, not the filter's: this DSL stores filters OIHW
        # ([Cout, Cin/g, kh, kw]) for both NCHW and NHWC data — so find
        # the Cout axis by matching the output's channel extent instead
        # of trusting the data layout (the old NHWC branch read
        # Cout·Cin·kh here, inflating ResNet-50 ~300x).
        nhwc = op.attrs.get("data_format", "NCHW") in ("NHWC", "NDHWC")
        cout = out_shape[-1] if nhwc else (
            out_shape[1] if len(out_shape) > 1 else out_shape[-1])
        if w_shape[0] == cout:
            per_out = _nelems(w_shape[1:])
        elif w_shape[-1] == cout:
            per_out = _nelems(w_shape[:-1])
        else:
            per_out = _nelems(w_shape) / max(float(cout), 1.0)
        return 2.0 * _nelems(out_shape) * per_out
    x = _first(op, "X")
    x_shape = _shape_of(env, block, x, default_dim) if x else None
    if x_shape is None:
        return 2.0 * _nelems(out_shape)
    if op.type == "mul":
        ncd = int(op.attrs.get("x_num_col_dims", 1) or 1)
        k = _nelems(x_shape[ncd:])
    else:  # matmul: contraction dim is x's last (or second-to-last if
        # transposed)
        k = x_shape[-2] if op.attrs.get("transpose_X") else x_shape[-1]
    return 2.0 * _nelems(out_shape) * float(max(int(k), 1))


def _attention_flops(op, env, block, default_dim) -> float:
    """fused_attention [B,H,Tq,Dh]x[B,H,Tk,Dh]: the two dots QK^T and
    W·V (2·M·K·N each => 4·Dh per score) plus softmax's ~3
    non-transcendental flops per score — what XLA counts for the
    equivalent unfused chain, so fused and unfused programs cost the
    same math."""
    q = _first(op, "Q")
    k = _first(op, "K")
    q_shape = _shape_of(env, block, q, default_dim) if q else None
    k_shape = _shape_of(env, block, k, default_dim) if k else None
    if q_shape is None or k_shape is None or len(q_shape) < 2 \
            or len(k_shape) < 2:
        out = next((n for n in op.output_arg_names if n != EMPTY_VAR),
                   None)
        out_shape = _shape_of(env, block, out, default_dim) if out else None
        return 2.0 * _nelems(out_shape) if out_shape else 0.0
    return ((4.0 * q_shape[-1] + 3.0)
            * _nelems(q_shape[:-1]) * float(k_shape[-2]))


def _op_flops(op, env, block, default_dim, fwd_by_out) -> float:
    t = op.type
    out_names = [n for n in op.output_arg_names if n != EMPTY_VAR]
    out_shapes = [s for s in (_shape_of(env, block, n, default_dim)
                              for n in out_names) if s is not None]
    out_elems = sum(_nelems(s) for s in out_shapes)
    if t in _MOVEMENT:
        return 0.0
    if t in _MATMUL_LIKE:
        return _matmul_flops(op, env, block, default_dim)
    if t == "fused_attention":
        return _attention_flops(op, env, block, default_dim)
    if t.endswith(GRAD_OP_SUFFIX):
        base = t[: -len(GRAD_OP_SUFFIX)]
        if base == "fused_attention":
            # flash backward: dV, dW, dQ, dK plus the W recompute —
            # ~2.5x the forward's dot work
            og = _first(op, "OutGrad")
            fwd = fwd_by_out.get(og.split(ir.GRAD_SUFFIX)[0]) if og else None
            if fwd is not None:
                return 2.5 * _attention_flops(fwd, env, block, default_dim)
            return 2.0 * out_elems
        if base in _MATMUL_LIKE:
            # one full product per produced input-grad (the 2x-forward
            # rule), costed from the forward op that made OutGrad's base
            og = _first(op, "OutGrad")
            fwd = fwd_by_out.get(og.split(ir.GRAD_SUFFIX)[0]) if og else None
            if fwd is not None:
                per = _matmul_flops(fwd, env, block, default_dim)
                n_grads = max(len(out_names), 1)
                return per * n_grads
            return 2.0 * out_elems
        if base in _MOVEMENT:
            return 0.0
        factor = _GRAD_ELEM_FLOPS.get(base, _ELEM_FLOPS.get(base, 1.0))
        return factor * max(out_elems, 1.0)
    if t in ("mean", "reduce_mean", "reduce_sum", "reduce_max"):
        ins = sum(_nelems(s) for s in
                  (_shape_of(env, block, n, default_dim)
                   for n in op.input_arg_names if n != EMPTY_VAR) if s)
        return float(ins)
    factor = _ELEM_FLOPS.get(t)
    if factor is not None:
        # normalization/softmax-family ops read more than they write; use
        # the dominant tensor (max of in/out elems) as the element count
        ins = [s for s in (_shape_of(env, block, n, default_dim)
                           for n in op.input_arg_names if n != EMPTY_VAR)
               if s is not None]
        elems = max([out_elems] + [_nelems(s) for s in ins])
        return factor * elems
    return float(max(out_elems, 1.0))   # unknown op: one flop per elem


def estimate_cost(program: ir.Program,
                  feed_shapes: Dict[str, Sequence[int]],
                  default_dim: Optional[int] = None) -> CostReport:
    """Static per-op FLOPs/bytes/memory for `program` with the given
    concrete feed shapes. `default_dim` substitutes any -1 the feeds
    don't resolve (defaults to the first feed's leading dim, else 1)."""
    if default_dim is None:
        default_dim = 1
        for shape in feed_shapes.values():
            if len(shape) and int(shape[0]) > 0:
                default_dim = int(shape[0])
                break
    unresolved: List[str] = []
    env = _concrete_env(program, feed_shapes, default_dim, unresolved)
    ops: List[OpCost] = []
    for block in program.blocks:
        fwd_by_out = {}
        for op in block.ops:
            if not op.type.endswith(GRAD_OP_SUFFIX) \
                    and op.type not in PSEUDO_OPS:
                for n in op.output_arg_names:
                    if n != EMPTY_VAR:
                        fwd_by_out[n] = op
        for op_idx, op in enumerate(block.ops):
            if op.type in PSEUDO_OPS:
                continue
            in_bytes = sum(_nbytes(env.get(n))
                           for n in op.input_arg_names if n != EMPTY_VAR)
            out_bytes = sum(_nbytes(env.get(n))
                            for n in op.output_arg_names if n != EMPTY_VAR)
            flops = _op_flops(op, env, block, default_dim, fwd_by_out)
            out0 = next((n for n in op.output_arg_names if n != EMPTY_VAR),
                        op.type)
            ops.append(OpCost(block.idx, op_idx, op.type, out0, flops,
                              in_bytes + out_bytes, out_bytes))
    param_bytes = 0.0
    for blk in program.blocks:
        for v in blk.vars.values():
            if v.persistable and v.shape != ():
                param_bytes += _nbytes(
                    (_resolve(v.shape, default_dim), v.dtype))
    return CostReport(ops, param_bytes, unresolved)


def shape_env(program: ir.Program,
              feed_shapes: Dict[str, Sequence[int]],
              default_dim: Optional[int] = None) -> Dict[str, ShapeDtype]:
    """The concrete {var: (shape, dtype)} environment `estimate_cost`
    walks — exposed for consumers that need per-tensor shapes next to
    the per-op table (the planner's communication model sizes ring/
    all-reduce payloads from the actual attention/grad tensors)."""
    if default_dim is None:
        default_dim = 1
        for shape in feed_shapes.values():
            if len(shape) and int(shape[0]) > 0:
                default_dim = int(shape[0])
                break
    return _concrete_env(program, feed_shapes, default_dim, [])


def estimate_peak_hbm(program: ir.Program,
                      feed_shapes: Dict[str, Sequence[int]],
                      default_dim: Optional[int] = None) -> dict:
    """fluid-pulse memory observatory: per-program peak-HBM estimate from
    the same concrete-shape walk `estimate_cost` uses.

    Decomposition (all bytes):

    - ``param_bytes``          persistable vars minus optimizer slots —
                               identical to CostReport.param_bytes minus
                               the slot component (their sum EQUALS
                               CostReport.param_bytes, test-pinned)
    - ``optimizer_slot_bytes`` persistable inputs of optimizer ops in
                               slots other than Param/Grad/LearningRate
                               (Velocity, Moment*, Beta*Pow, ...)
    - ``grad_bytes``           non-persistable GRAD-suffixed vars — the
                               dualed gradients live until applied
    - ``activation_bytes``     every other non-persistable intermediate
                               the walk resolved (forward activations a
                               training step keeps for the backward)
    - ``feed_bytes``           the fed batch itself
    - ``peak_bytes``           the sum — an upper-bound-flavored estimate
                               (XLA frees/fuses intermediates it can,
                               and adds workspace/padding it must; see
                               docs/OBSERVABILITY.md §memory for the
                               band measured on the book models)
    """
    if default_dim is None:
        default_dim = 1
        for shape in feed_shapes.values():
            if len(shape) and int(shape[0]) > 0:
                default_dim = int(shape[0])
                break
    unresolved: List[str] = []
    env = _concrete_env(program, feed_shapes, default_dim, unresolved)

    slot_names: set = set()
    for block in program.blocks:
        for op in block.ops:
            ins = op.inputs
            if "Param" not in ins or "Grad" not in ins:
                continue
            for slot, names in ins.items():
                if slot in ("Param", "Grad", "LearningRate"):
                    continue
                slot_names.update(n for n in names if n != EMPTY_VAR)

    params = slots = grads = acts = feeds = 0.0
    seen: set = set()
    for blk in program.blocks:
        for v in blk.vars.values():
            if v.name in seen or v.shape == ():
                continue
            seen.add(v.name)
            nb = _nbytes(env.get(v.name)
                         or (_resolve(v.shape, default_dim), v.dtype))
            if v.persistable:
                if v.name in slot_names:
                    slots += nb
                else:
                    params += nb
            elif v.is_data or v.name in feed_shapes:
                feeds += nb
            elif ir.GRAD_SUFFIX in v.name:
                grads += nb
            else:
                acts += nb
    return {
        "param_bytes": params,
        "optimizer_slot_bytes": slots,
        "grad_bytes": grads,
        "activation_bytes": acts,
        "feed_bytes": feeds,
        "peak_bytes": params + slots + grads + acts + feeds,
        "unresolved": len(unresolved),
    }


def xla_flops(exe, scope, feed_arrays) -> float:
    """Ground truth for the cross-check: FLOPs XLA counts for the largest
    step compiled in `exe` (the program must have run once with
    `feed_arrays`). Same private-API dance as tools/_common.py's
    compile_main_step, inlined so the package has no tools/ dependency."""
    compiled = max(exe._cache.values(),
                   key=lambda c: len(c.program.global_block().ops))
    mut = {n: scope.find_var(n) for n in compiled.mut_names}
    const = {n: scope.find_var(n) for n in compiled.const_names}
    feeds = {k: feed_arrays[k] for k in sorted(feed_arrays)}
    ca = (compiled._step.lower(feeds, mut, const, np.uint32(0))
          .compile().cost_analysis())
    if isinstance(ca, (list, tuple)):   # older jax: one dict per partition
        ca = ca[0] if ca else {}
    return float(ca.get("flops", 0.0))
