"""fluid-sentry: concurrency static analysis over the repo's own Python.

The analysis package verifies the Program IR at build time; this module
turns the same discipline on the *runtime* — the four heavily threaded
HA planes (master, haven, quorum, fleet) plus pserver/serve, whose only
correctness net so far is chaos drills, which sample schedules instead
of proving them. An AST pass models every class: the threads it spawns
(`threading.Thread`/`Timer` targets, executor `.submit` callees, and the
intra-class call graph reachable from them), its lock attributes, and
its shared mutable fields. On top of that model it enforces three
properties, each surfaced as a ranked `Diagnostic` (diagnostics.py):

**Lock discipline** — a field annotated `# guarded_by: self._mu` on its
`__init__` assignment must be read and written with `self._mu` held.

    ``unguarded-write`` (ERROR)    write with no lock held
    ``unguarded-read``  (WARNING)  read with no lock held
    ``guard-mismatch``  (WARNING)  access under a *different* lock
    ``guard-inference`` (INFO)     majority-usage proposal for an
                                   unannotated cross-thread field

Unannotated fields that are demonstrably cross-thread (written in the
spawned-thread domain, touched outside it, or vice versa) get
majority-usage inference: if >= RATIO of their accesses happen under one
lock, that lock is proposed as the guard and the outlier accesses are
flagged at WARNING (never ERROR — the contract was inferred, not
declared).

**Deadlock cycles** — every acquisition taken while another lock is
held contributes an edge to a global acquires-while-holding graph whose
nodes are ``Class.lock`` (conditions normalize to the mutex they wrap).
Cross-class edges come from attribute types inferred from
``self.x = ClassName(...)`` in ``__init__``: holding my lock while
calling a method of a class that takes its own lock links the planes
(FleetRouter -> PSClient is exactly such an edge). A cycle — including
a self-cycle on a non-reentrant ``threading.Lock`` — is
``lock-order-cycle`` (ERROR).

**Hold-time hazards** — ``blocking-under-lock`` (WARNING): `time.sleep`,
socket/RPC primitives (`send_msg`, `recv_msg`, `connect`, `accept`,
...), `Condition.wait()` **without a timeout**, or `.join()` without a
timeout, executed while a lock is held that the call does not itself
release (a condition's own wait releases its wrapped mutex, so only
*additional* held locks count). Calls to intra-class or attribute-typed
methods that transitively block are flagged at the call site. On the
lease-renewal paths this is the lint that defends the ~0.7 s
failover-blip budget.

Held-lock state is tracked through ``with`` blocks, paired
``.acquire()``/``.release()`` statements, and *interprocedurally*: a
private method's entry held-set is the intersection of the held-sets at
every intra-class call site (public, dunder, and thread-root methods get
an implicit lock-free external caller). ``__init__`` is pre-publication
and exempt from discipline checks.

Suppression: a trailing ``# race_lint: ignore[code]`` (or a bare
``# race_lint: ignore``) on the flagged line, or
``# race_lint: skip-file`` anywhere in the first 10 lines of a module.
Nested function/lambda bodies execute on an unknowable thread at an
unknowable time and are skipped (documented limitation).

`tools/race_lint.py` is the CLI; `tools/race_lint_baseline.json` pins
the reviewed residue so CI (tests/test_race_lint.py) fails only on NEW
findings. Baseline keys deliberately omit line numbers:
``code path Class.member detail`` survives unrelated edits to the file.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field as dc_field
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from .diagnostics import Diagnostic, Severity

__all__ = [
    "ConcurrencyDiagnostic", "analyze_source", "analyze_paths",
    "analyze_package", "baseline_key", "CODES",
]

CODES = ("unguarded-write", "unguarded-read", "guard-mismatch",
         "lock-order-cycle", "blocking-under-lock", "guard-inference")

# majority-usage inference: >= this fraction of a cross-thread field's
# accesses under one lock proposes that lock as the guard
_INFER_RATIO = 0.70
_INFER_MIN_SITES = 3

# lock-ish constructors (threading.*). Event is tracked for .wait()
# classification but is NOT a mutex — it never guards anything.
_MUTEX_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
                "BoundedSemaphore"}
_EVENT_CTORS = {"Event"}

# callables that block the calling thread for unbounded / network time.
# Names are matched on the called attribute (x.recv(...)) or the dotted
# tail of a module call (time.sleep, select.select). send_msg/recv_msg
# are the repo's own framed-RPC primitives (pserver/rpc.py, fleet/wire).
_BLOCKING_NAMES = frozenset({
    "sleep", "send_msg", "recv_msg", "sendall", "recv", "recvfrom",
    "accept", "connect", "create_connection", "getaddrinfo", "urlopen",
    "select",
})
# blocking only when called with NO timeout argument
_TIMEOUT_GATED = frozenset({"wait", "join", "result", "get"})

_GUARD_RE = re.compile(r"#\s*guarded_by:\s*(self\.\w+(?:\(\))?)")
_IGNORE_RE = re.compile(r"#\s*race_lint:\s*ignore(?:\[([\w\-,\s]+)\])?")
_SKIP_FILE_RE = re.compile(r"#\s*race_lint:\s*skip-file")


@dataclass
class ConcurrencyDiagnostic(Diagnostic):
    """A Diagnostic plus the stable provenance race_lint baselines on:
    (path, Class.member, detail) — no line numbers, so a key survives
    unrelated edits to the file."""

    path: str = ""        # repo-relative path
    qual: str = ""        # Class.field or Class.method
    detail: str = ""      # guard name / blocked call / cycle lock list
    line: int = 0


def baseline_key(d: ConcurrencyDiagnostic) -> str:
    return f"{d.code} {d.path} {d.qual} {d.detail}"


# ---------------------------------------------------------------------------
# per-class model
# ---------------------------------------------------------------------------

@dataclass
class _Lock:
    name: str                       # attribute name, e.g. "_mu"
    kind: str                       # Lock | RLock | Condition | ...
    wraps: Optional[str] = None     # Condition(self._mu) -> "_mu"
    line: int = 0
    is_event: bool = False


@dataclass
class _Field:
    name: str
    guard: Optional[str] = None     # annotated guard token (normalized)
    line: int = 0


@dataclass
class _Access:
    field: str
    kind: str                       # "read" | "write"
    method: str
    line: int
    held: FrozenSet[str]            # local held tokens (pre-entry-set)


@dataclass
class _Acquire:
    lock: str                       # token being acquired
    method: str
    line: int
    held: FrozenSet[str]


@dataclass
class _Blocking:
    desc: str                       # e.g. "time.sleep" / "sock.recv_msg"
    method: str
    line: int
    held: FrozenSet[str]
    releases: FrozenSet[str]        # root mutexes the call itself releases


@dataclass
class _XCall:
    """self.<attr>.<meth>(...) — a call into another modeled class."""
    attr: str
    meth: str
    method: str
    line: int
    held: FrozenSet[str]


@dataclass
class _ClassModel:
    name: str
    path: str
    line: int
    locks: Dict[str, _Lock] = dc_field(default_factory=dict)
    fields: Dict[str, _Field] = dc_field(default_factory=dict)
    thread_roots: Set[str] = dc_field(default_factory=set)
    attr_types: Dict[str, str] = dc_field(default_factory=dict)
    calls: Dict[str, List[Tuple[str, FrozenSet[str]]]] = \
        dc_field(default_factory=dict)   # caller -> [(callee, held@site)]
    methods: Set[str] = dc_field(default_factory=set)
    accesses: List[_Access] = dc_field(default_factory=list)
    acquires: List[_Acquire] = dc_field(default_factory=list)
    blocking: List[_Blocking] = dc_field(default_factory=list)
    xcalls: List[_XCall] = dc_field(default_factory=list)
    entry_held: Dict[str, FrozenSet[str]] = dc_field(default_factory=dict)

    def root(self, token: str) -> str:
        """Normalize a lock token to the mutex actually contended:
        a Condition built over another lock IS that lock."""
        lk = self.locks.get(token)
        if lk is not None and lk.wraps and lk.wraps in self.locks \
                and lk.wraps != token:
            return self.root(lk.wraps)
        return token

    def roots(self, tokens: FrozenSet[str]) -> FrozenSet[str]:
        return frozenset(self.root(t) for t in tokens)


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------

def _self_attr(node: ast.AST) -> Optional[str]:
    """'self.X' -> 'X'; anything else -> None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _lock_token(node: ast.AST) -> Optional[str]:
    """A lock-valued expression: self.X -> 'X';
    self.X(...) (per-key lock factory) -> 'X()'."""
    a = _self_attr(node)
    if a is not None:
        return a
    if isinstance(node, ast.Call):
        a = _self_attr(node.func)
        if a is not None:
            return a + "()"
    return None


def _call_tail(func: ast.AST) -> Optional[str]:
    """Last attribute of a call target: time.sleep -> 'sleep'."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _ctor_name(call: ast.Call) -> Optional[str]:
    """threading.RLock() -> 'RLock'; RLock() -> 'RLock'."""
    return _call_tail(call.func)


def _has_timeout(call: ast.Call) -> bool:
    if call.args:
        return True
    return any(kw.arg in ("timeout", "timeout_s") for kw in call.keywords)


class _MethodWalker:
    """Walk one method body tracking the locally held lock set."""

    def __init__(self, cm: _ClassModel, method: str):
        self.cm = cm
        self.method = method

    # -- statements --------------------------------------------------------

    def walk(self, stmts: Sequence[ast.stmt], held: Set[str]):
        held = set(held)
        for st in stmts:
            self._stmt(st, held)

    def _stmt(self, st: ast.stmt, held: Set[str]):
        if isinstance(st, ast.With) or isinstance(st, ast.AsyncWith):
            added = []
            for item in st.items:
                tok = _lock_token(item.context_expr)
                if tok is not None and self._is_lockish(tok):
                    self._record_acquire(tok, item.context_expr.lineno,
                                         held)
                    added.append(tok)
                else:
                    self._expr(item.context_expr, held)
            inner = set(held) | set(added)
            self.walk(st.body, inner)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda, ast.ClassDef)):
            return   # deferred execution context: skipped (see docstring)
        if isinstance(st, ast.Expr):
            call = st.value
            if isinstance(call, ast.Call):
                tail = _call_tail(call.func)
                recv = call.func.value if isinstance(call.func,
                                                     ast.Attribute) else None
                tok = _lock_token(recv) if recv is not None else None
                if tail == "acquire" and tok and self._is_lockish(tok):
                    self._record_acquire(tok, st.lineno, held)
                    held.add(tok)
                    return
                if tail == "release" and tok and tok in held:
                    held.discard(tok)
                    return
            self._expr(st.value, held)
            return
        if isinstance(st, (ast.If,)):
            self._expr(st.test, held)
            self.walk(st.body, held)
            self.walk(st.orelse, held)
            return
        if isinstance(st, (ast.While,)):
            self._expr(st.test, held)
            self.walk(st.body, held)
            self.walk(st.orelse, held)
            return
        if isinstance(st, ast.For):
            self._target(st.target, held)
            self._expr(st.iter, held)
            self.walk(st.body, held)
            self.walk(st.orelse, held)
            return
        if isinstance(st, ast.Try):
            self.walk(st.body, held)
            for h in st.handlers:
                self.walk(h.body, held)
            self.walk(st.orelse, held)
            self.walk(st.finalbody, held)
            return
        if isinstance(st, ast.Return) and st.value is not None:
            self._expr(st.value, held)
            return
        if isinstance(st, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._assign(st, held)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                self._target(t, held, delete=True)
            return
        if isinstance(st, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._expr(child, held)
            return
        # anything else: visit child expressions generically
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._expr(child, held)
            elif isinstance(child, ast.stmt):
                self._stmt(child, held)

    # -- assignment targets -----------------------------------------------

    def _assign(self, st: ast.stmt, held: Set[str]):
        if isinstance(st, ast.Assign):
            value, targets = st.value, st.targets
        elif isinstance(st, ast.AugAssign):
            value, targets = st.value, [st.target]
            # aug-assign reads then writes the target
            self._expr_attr_read(st.target, held)
        else:   # AnnAssign
            value, targets = st.value, [st.target]
        if value is not None:
            self._expr(value, held)
        for t in targets:
            self._target(t, held)

    def _target(self, t: ast.expr, held: Set[str], delete: bool = False):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._target(e, held, delete)
            return
        a = _self_attr(t)
        if a is not None:
            self._access(a, "write", t.lineno, held)
            return
        if isinstance(t, ast.Subscript):
            # self.X[k] = v  mutates the container held in self.X
            a = _self_attr(t.value)
            if a is not None:
                self._access(a, "write", t.lineno, held)
            else:
                self._expr(t.value, held)
            self._expr(t.slice, held)
            return
        if isinstance(t, ast.Attribute):
            # x.attr = v where x is not self: read x
            self._expr(t.value, held)
            return
        if isinstance(t, ast.Starred):
            self._target(t.value, held, delete)

    def _expr_attr_read(self, t: ast.expr, held: Set[str]):
        a = _self_attr(t)
        if a is not None:
            self._access(a, "read", t.lineno, held)
        elif isinstance(t, ast.Subscript):
            a = _self_attr(t.value)
            if a is not None:
                self._access(a, "read", t.lineno, held)

    # -- expressions -------------------------------------------------------

    _MUTATORS = frozenset({
        "append", "extend", "insert", "pop", "popitem", "remove",
        "discard", "clear", "update", "setdefault", "add",
        "appendleft", "popleft", "sort", "reverse",
    })

    def _expr(self, e: ast.expr, held: Set[str]):
        if e is None:
            return
        if isinstance(e, (ast.Lambda,)):
            return
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.DictComp,
                          ast.GeneratorExp)):
            # comprehensions run inline on this thread: walk them
            for gen in e.generators:
                self._expr(gen.iter, held)
                for cond in gen.ifs:
                    self._expr(cond, held)
            if isinstance(e, ast.DictComp):
                self._expr(e.key, held)
                self._expr(e.value, held)
            else:
                self._expr(e.elt, held)
            return
        if isinstance(e, ast.Call):
            self._call(e, held)
            return
        a = _self_attr(e)
        if a is not None:
            self._access(a, "read", e.lineno, held)
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                self._expr(child, held)

    def _call(self, call: ast.Call, held: Set[str]):
        cm, fs = self.cm, frozenset(held)
        tail = _call_tail(call.func)
        func = call.func

        # thread roots: Thread(target=self.m) / Timer(t, self.m) /
        # executor.submit(self.m, ...)
        self._maybe_thread_root(call, tail)

        handled_recv = False
        if isinstance(func, ast.Attribute):
            recv = func.value
            recv_attr = _self_attr(recv)

            if isinstance(recv, ast.Name) and recv.id == "self":
                # self.<meth>(...)
                cm.calls.setdefault(self.method, []).append((func.attr, fs))
                handled_recv = True
            elif recv_attr is not None:
                # self.<attr>.<meth>(...)
                tok = cm.root(recv_attr) if recv_attr in cm.locks else None
                if tail in ("acquire",) and recv_attr in cm.locks:
                    self._record_acquire(recv_attr, call.lineno, held)
                    handled_recv = True
                elif tail in _TIMEOUT_GATED and not _has_timeout(call):
                    rel = frozenset({tok}) if (
                        tok is not None and
                        cm.locks[recv_attr].kind == "Condition") else \
                        frozenset()
                    cm.blocking.append(_Blocking(
                        f"self.{recv_attr}.{tail}() without timeout",
                        self.method, call.lineno, fs, rel))
                    self._access_maybe(recv_attr, call.lineno, held)
                    handled_recv = True
                elif tail in _BLOCKING_NAMES:
                    cm.blocking.append(_Blocking(
                        f"self.{recv_attr}.{tail}()", self.method,
                        call.lineno, fs, frozenset()))
                    self._access_maybe(recv_attr, call.lineno, held)
                    handled_recv = True
                elif tail in self._MUTATORS:
                    self._access(recv_attr, "write", call.lineno, held)
                    handled_recv = True
                elif recv_attr in cm.attr_types:
                    cm.xcalls.append(_XCall(recv_attr, tail, self.method,
                                            call.lineno, fs))
                    self._access_maybe(recv_attr, call.lineno, held)
                    handled_recv = True
                else:
                    self._access_maybe(recv_attr, call.lineno, held)
                    handled_recv = True
            else:
                # module-or-object call: time.sleep, sock.recv, ...
                base = recv.id if isinstance(recv, ast.Name) else None
                if tail in _BLOCKING_NAMES:
                    who = f"{base}.{tail}" if base else tail
                    cm.blocking.append(_Blocking(
                        who, self.method, call.lineno, fs, frozenset()))
                elif tail in _TIMEOUT_GATED and not _has_timeout(call):
                    who = f"{base}.{tail}" if base else tail
                    cm.blocking.append(_Blocking(
                        f"{who}() without timeout", self.method,
                        call.lineno, fs, frozenset()))
            if not handled_recv:
                self._expr(recv, held)
        elif isinstance(func, ast.Name):
            if tail in _BLOCKING_NAMES:
                cm.blocking.append(_Blocking(
                    tail, self.method, call.lineno, fs, frozenset()))

        for a in call.args:
            self._expr(a, held)
        for kw in call.keywords:
            self._expr(kw.value, held)

    def _maybe_thread_root(self, call: ast.Call, tail: Optional[str]):
        cm = self.cm
        cand: List[ast.expr] = []
        if tail in ("Thread", "Timer"):
            for kw in call.keywords:
                if kw.arg in ("target", "function"):
                    cand.append(kw.value)
            if tail == "Timer" and len(call.args) >= 2:
                cand.append(call.args[1])
        elif tail in ("submit", "map", "run_in_executor"):
            if call.args:
                cand.append(call.args[0])
        for c in cand:
            a = _self_attr(c)
            if a is not None:
                cm.thread_roots.add(a)

    # -- event recording ---------------------------------------------------

    def _is_lockish(self, tok: str) -> bool:
        if tok.endswith("()"):
            # per-key lock factory (`with self._lock(name):`) — only
            # names that say so; arbitrary contextmanager methods
            # (`with self.quiesce():`) are not lock acquisitions
            return "lock" in tok.lower() or "mutex" in tok.lower()
        lk = self.cm.locks.get(tok)
        return lk is not None and not lk.is_event

    def _record_acquire(self, tok: str, line: int, held: Set[str]):
        self.cm.acquires.append(
            _Acquire(tok, self.method, line, frozenset(held)))

    def _access_maybe(self, attr: str, line: int, held: Set[str]):
        """Receiver of a method call on self.X counts as a read of X
        (unknown methods are treated as non-mutating)."""
        if attr in self.cm.locks:
            return
        self._access(attr, "read", line, held)

    def _access(self, attr: str, kind: str, line: int, held: Set[str]):
        if attr in self.cm.locks or attr in self.cm.methods:
            return
        self.cm.accesses.append(
            _Access(attr, kind, self.method, line, frozenset(held)))


def _extract_class(node: ast.ClassDef, path: str,
                   lines: List[str]) -> _ClassModel:
    cm = _ClassModel(name=node.name, path=path, line=node.lineno)
    body_methods = [n for n in node.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))]
    cm.methods = {m.name for m in body_methods}

    # pass 1: __init__ — locks, fields (+ guard annotations), attr types
    for m in body_methods:
        if m.name != "__init__":
            continue
        for st in ast.walk(m):
            if not isinstance(st, (ast.Assign, ast.AnnAssign)):
                continue
            targets = st.targets if isinstance(st, ast.Assign) else \
                [st.target]
            value = st.value
            for t in targets:
                a = _self_attr(t)
                if a is None:
                    continue
                if isinstance(value, ast.Call):
                    ctor = _ctor_name(value)
                    if ctor in _MUTEX_CTORS:
                        wraps = None
                        if ctor == "Condition" and value.args:
                            wraps = _self_attr(value.args[0])
                        cm.locks[a] = _Lock(a, ctor, wraps, t.lineno)
                        continue
                    if ctor in _EVENT_CTORS:
                        cm.locks[a] = _Lock(a, ctor, None, t.lineno,
                                            is_event=True)
                        continue
                    if ctor and ctor[0].isupper():
                        cm.attr_types[a] = ctor
                if a not in cm.fields:
                    guard = _guard_annotation(lines, t.lineno)
                    cm.fields[a] = _Field(a, guard, t.lineno)

    # fields assigned a lock later should not double as plain fields
    for lk in cm.locks:
        cm.fields.pop(lk, None)

    # pass 2: walk every method
    for m in body_methods:
        if m.name == "__init__":
            # still collect thread roots + attr types from __init__ body
            w = _MethodWalker(cm, "__init__")
            w.walk(m.body, set())
            continue
        w = _MethodWalker(cm, m.name)
        w.walk(m.body, set())

    # __init__ accesses are pre-publication: drop them from discipline
    cm.accesses = [a for a in cm.accesses if a.method != "__init__"]
    cm.blocking = [b for b in cm.blocking if b.method != "__init__"]
    cm.acquires = [a for a in cm.acquires if a.method != "__init__"]
    cm.xcalls = [x for x in cm.xcalls if x.method != "__init__"]
    return cm


def _guard_annotation(lines: List[str], lineno: int) -> Optional[str]:
    """`# guarded_by: self._mu` trailing the assignment line, or on a
    pure-comment line directly above it (for assignments too long to
    carry a trailing comment). Returns the normalized token ('_mu' or
    '_mu()')."""
    if 1 <= lineno <= len(lines):
        mm = _GUARD_RE.search(lines[lineno - 1])
        if mm:
            return mm.group(1)[len("self."):]
    if 2 <= lineno and lines[lineno - 2].lstrip().startswith("#"):
        mm = _GUARD_RE.search(lines[lineno - 2])
        if mm:
            return mm.group(1)[len("self."):]
    return None


# ---------------------------------------------------------------------------
# interprocedural closures
# ---------------------------------------------------------------------------

def _compute_entry_held(cm: _ClassModel):
    """Fixpoint: a private method called only with lock L held inherits
    {L}; public/dunder/thread-root methods get an implicit external
    caller holding nothing. Intersection over call sites keeps this an
    under-approximation (never invents a held lock)."""
    TOP = None
    entry: Dict[str, Optional[FrozenSet[str]]] = {}
    for mth in cm.methods:
        external = (not mth.startswith("_") or
                    (mth.startswith("__") and mth.endswith("__")) or
                    mth in cm.thread_roots)
        entry[mth] = frozenset() if external else TOP
    changed = True
    while changed:
        changed = False
        for caller, sites in cm.calls.items():
            caller_entry = entry.get(caller)
            if caller_entry is None:
                continue    # unreached so far
            for callee, held in sites:
                if callee not in entry:
                    continue
                eff = frozenset(caller_entry | held)
                cur = entry[callee]
                new = eff if cur is None else (cur & eff)
                if new != cur:
                    entry[callee] = new
                    changed = True
    cm.entry_held = {m: (s if s is not None else frozenset())
                     for m, s in entry.items()}


def _thread_domain(cm: _ClassModel) -> Set[str]:
    """Methods reachable (intra-class) from spawned-thread roots."""
    seen = set(r for r in cm.thread_roots if r in cm.methods)
    work = list(seen)
    while work:
        m = work.pop()
        for callee, _ in cm.calls.get(m, []):
            if callee in cm.methods and callee not in seen:
                seen.add(callee)
                work.append(callee)
    return seen


def _may_block(corpus: Dict[str, _ClassModel]
               ) -> Dict[Tuple[str, str], str]:
    """(class, method) -> witness description, for methods that reach a
    blocking call on some path; propagated through intra-class calls and
    attribute-typed cross-class calls."""
    out: Dict[Tuple[str, str], str] = {}
    for cm in corpus.values():
        for b in cm.blocking:
            out.setdefault((cm.name, b.method), b.desc)
    changed = True
    while changed:
        changed = False
        for cm in corpus.values():
            for caller, sites in cm.calls.items():
                if (cm.name, caller) in out:
                    continue
                for callee, _ in sites:
                    w = out.get((cm.name, callee))
                    if w is not None:
                        out[(cm.name, caller)] = \
                            f"self.{callee}() -> {w}"
                        changed = True
                        break
            for x in cm.xcalls:
                if (cm.name, x.method) in out:
                    continue
                tgt = corpus.get(cm.attr_types.get(x.attr, ""))
                if tgt is None:
                    continue
                w = out.get((tgt.name, x.meth))
                if w is not None:
                    out[(cm.name, x.method)] = \
                        f"self.{x.attr}.{x.meth}() -> {w}"
                    changed = True
    return out


# ---------------------------------------------------------------------------
# checks
# ---------------------------------------------------------------------------

def _eff_held(cm: _ClassModel, method: str,
              held: FrozenSet[str]) -> FrozenSet[str]:
    return cm.roots(held | cm.entry_held.get(method, frozenset()))


class _Suppressions:
    def __init__(self, lines: List[str]):
        self.lines = lines

    def active(self, line: int, code: str) -> bool:
        if not (1 <= line <= len(self.lines)):
            return False
        mm = _IGNORE_RE.search(self.lines[line - 1])
        if not mm:
            return False
        if mm.group(1) is None:
            return True
        return code in {c.strip() for c in mm.group(1).split(",")}


def _check_guards(cm: _ClassModel, sup: _Suppressions
                  ) -> List[ConcurrencyDiagnostic]:
    diags: List[ConcurrencyDiagnostic] = []
    tdom = _thread_domain(cm)
    has_threads = bool(tdom)

    by_field: Dict[str, List[_Access]] = {}
    for a in cm.accesses:
        if a.field in cm.fields or a.field not in cm.attr_types:
            by_field.setdefault(a.field, []).append(a)

    for fname, accs in sorted(by_field.items()):
        fld = cm.fields.get(fname)
        guard = cm.root(fld.guard) if fld and fld.guard else None
        if guard is not None:
            diags.extend(_check_annotated(cm, fname, guard, accs, sup))
        elif has_threads:
            diags.extend(_infer_guard(cm, fname, accs, tdom, sup))
    return diags


def _mk(cm: _ClassModel, code: str, sev: Severity, msg: str, qual: str,
        detail: str, line: int) -> ConcurrencyDiagnostic:
    return ConcurrencyDiagnostic(
        code=code, severity=sev, message=msg, var=qual,
        site=[f"{cm.path}:{line} in {qual}"],
        path=cm.path, qual=qual, detail=detail, line=line)


def _check_annotated(cm: _ClassModel, fname: str, guard: str,
                     accs: List[_Access], sup: _Suppressions
                     ) -> List[ConcurrencyDiagnostic]:
    diags = []
    for a in accs:
        held = _eff_held(cm, a.method, a.held)
        if guard in held:
            continue
        qual = f"{cm.name}.{fname}"
        mqual = f"{cm.name}.{a.method}"
        if held:
            code, sev = "guard-mismatch", Severity.WARNING
            msg = (f"{qual} is annotated guarded_by self.{guard} but "
                   f"{a.kind} in {a.method}() holds "
                   f"{{{', '.join('self.' + h for h in sorted(held))}}} "
                   f"instead")
        elif a.kind == "write":
            code, sev = "unguarded-write", Severity.ERROR
            msg = (f"{qual} is annotated guarded_by self.{guard} but "
                   f"written in {a.method}() with no lock held")
        else:
            code, sev = "unguarded-read", Severity.WARNING
            msg = (f"{qual} is annotated guarded_by self.{guard} but "
                   f"read in {a.method}() with no lock held")
        if sup.active(a.line, code):
            continue
        diags.append(_mk(cm, code, sev, msg, qual,
                         f"{a.kind}@{mqual}", a.line))
    return diags


def _infer_guard(cm: _ClassModel, fname: str, accs: List[_Access],
                 tdom: Set[str], sup: _Suppressions
                 ) -> List[ConcurrencyDiagnostic]:
    """Majority-usage inference for unannotated fields that are shared
    across the thread boundary."""
    in_thread = [a for a in accs if a.method in tdom]
    outside = [a for a in accs if a.method not in tdom]
    wrote = any(a.kind == "write" for a in accs)
    if not (in_thread and outside and wrote):
        return []
    if len(accs) < _INFER_MIN_SITES:
        return []
    counts: Dict[str, int] = {}
    for a in accs:
        for h in _eff_held(cm, a.method, a.held):
            counts[h] = counts.get(h, 0) + 1
    if not counts:
        return []
    guard, n = max(counts.items(), key=lambda kv: (kv[1], kv[0]))
    if n / len(accs) < _INFER_RATIO:
        return []
    qual = f"{cm.name}.{fname}"
    diags = [_mk(
        cm, "guard-inference", Severity.INFO,
        f"{qual} is accessed from both the spawned-thread and caller "
        f"domains; {n}/{len(accs)} accesses hold self.{guard} — "
        f"annotate it `# guarded_by: self.{guard}`",
        qual, f"self.{guard}", cm.fields[fname].line
        if fname in cm.fields else accs[0].line)]
    for a in accs:
        held = _eff_held(cm, a.method, a.held)
        if guard in held:
            continue
        code = "unguarded-write" if a.kind == "write" else "unguarded-read"
        if sup.active(a.line, code):
            continue
        mqual = f"{cm.name}.{a.method}"
        verb = "written" if a.kind == "write" else "read"
        diags.append(_mk(
            cm, code, Severity.WARNING,
            f"{qual} is {verb} in {a.method}() without "
            f"self.{guard}, the inferred guard ({n}/{len(accs)} other "
            f"accesses hold it)",
            qual, f"{a.kind}@{mqual}", a.line))
    return diags


def _check_blocking(cm: _ClassModel, corpus: Dict[str, _ClassModel],
                    may_block: Dict[Tuple[str, str], str],
                    sup: _Suppressions) -> List[ConcurrencyDiagnostic]:
    diags = []
    seen: Set[Tuple[str, int]] = set()

    def emit(desc: str, method: str, line: int,
             held: FrozenSet[str], releases: FrozenSet[str]):
        eff = _eff_held(cm, method, held) - cm.roots(releases)
        if not eff or (method, line) in seen:
            return
        if sup.active(line, "blocking-under-lock"):
            return
        seen.add((method, line))
        qual = f"{cm.name}.{method}"
        locks = ", ".join("self." + h for h in sorted(eff))
        diags.append(_mk(
            cm, "blocking-under-lock", Severity.WARNING,
            f"{qual}() calls {desc} while holding {{{locks}}} — the "
            f"lock is pinned for the full blocking duration (hold-time "
            f"hazard; on a renewal path this eats the failover budget)",
            qual, desc.split("(")[0].strip(), line))

    for b in cm.blocking:
        emit(b.desc, b.method, b.line, b.held, b.releases)
    # calls into methods that transitively block
    for caller, sites in cm.calls.items():
        for callee, held in sites:
            w = may_block.get((cm.name, callee))
            if w is None:
                continue
            # the callee's own frame reports it when it holds the lock
            # itself; here we only report locks held at THIS call site
            line = _call_line(cm, caller, callee)
            emit(f"self.{callee}() [{w}]", caller, line, held,
                 frozenset())
    for x in cm.xcalls:
        tgt = corpus.get(cm.attr_types.get(x.attr, ""))
        if tgt is None:
            continue
        w = may_block.get((tgt.name, x.meth))
        if w is not None:
            emit(f"self.{x.attr}.{x.meth}() [{w}]", x.method, x.line,
                 x.held, frozenset())
    return diags


def _call_line(cm: _ClassModel, caller: str, callee: str) -> int:
    # call sites keep no line today; anchor on the caller's acquires or
    # the class line — the baseline key is line-free anyway
    for a in cm.acquires:
        if a.method == caller:
            return a.line
    return cm.line


def _lock_graph(corpus: Dict[str, _ClassModel]
                ) -> Tuple[Dict[str, Set[str]],
                           Dict[Tuple[str, str], Tuple[str, int, str]]]:
    """Nodes 'Class.lock' (root-normalized); edge A->B when B is
    acquired while A is held. Returns (adjacency, edge witness)."""
    adj: Dict[str, Set[str]] = {}
    wit: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add(a: str, b: str, path: str, line: int, desc: str):
        adj.setdefault(a, set()).add(b)
        adj.setdefault(b, set())
        wit.setdefault((a, b), (path, line, desc))

    for cm in corpus.values():
        for ac in cm.acquires:
            tgt = cm.root(ac.lock)
            node_b = f"{cm.name}.{tgt}"
            for h in _eff_held(cm, ac.method, ac.held):
                if h == tgt:
                    # re-acquire of the same mutex: only a deadlock on a
                    # non-reentrant plain Lock
                    lk = cm.locks.get(tgt)
                    if lk is not None and lk.kind == "Lock":
                        add(node_b, node_b, cm.path, ac.line,
                            f"{cm.name}.{ac.method}() re-acquires "
                            f"non-reentrant self.{tgt}")
                    continue
                add(f"{cm.name}.{h}", node_b, cm.path, ac.line,
                    f"{cm.name}.{ac.method}() acquires self.{tgt} "
                    f"while holding self.{h}")
        # cross-class: holding my lock, calling into a typed attribute
        for x in cm.xcalls:
            tgt_cm = corpus.get(cm.attr_types.get(x.attr, ""))
            if tgt_cm is None:
                continue
            held_here = _eff_held(cm, x.method, x.held)
            if not held_here:
                continue
            for lock in _locks_taken_by(tgt_cm, x.meth, corpus):
                for h in held_here:
                    add(f"{cm.name}.{h}", lock, cm.path, x.line,
                        f"{cm.name}.{x.method}() holds self.{h} and "
                        f"calls self.{x.attr}.{x.meth}() which "
                        f"acquires {lock}")
    return adj, wit


def _locks_taken_by(cm: _ClassModel, method: str,
                    corpus: Dict[str, _ClassModel],
                    _depth: int = 0) -> Set[str]:
    """Root-normalized 'Class.lock' nodes a method may acquire,
    following intra-class calls (and one more class hop)."""
    out: Set[str] = set()
    seen: Set[str] = set()
    work = [method]
    while work:
        m = work.pop()
        if m in seen:
            continue
        seen.add(m)
        for ac in cm.acquires:
            if ac.method == m and not ac.lock.endswith("()"):
                out.add(f"{cm.name}.{cm.root(ac.lock)}")
        for callee, _ in cm.calls.get(m, []):
            if callee in cm.methods:
                work.append(callee)
        if _depth < 1:
            for x in cm.xcalls:
                if x.method != m:
                    continue
                nxt = corpus.get(cm.attr_types.get(x.attr, ""))
                if nxt is not None:
                    out |= _locks_taken_by(nxt, x.meth, corpus,
                                           _depth + 1)
    return out


def _check_cycles(corpus: Dict[str, _ClassModel]
                  ) -> List[ConcurrencyDiagnostic]:
    adj, wit = _lock_graph(corpus)
    diags: List[ConcurrencyDiagnostic] = []

    # self-cycles first (non-reentrant re-acquire)
    for a in sorted(adj):
        if a in adj[a]:
            path, line, desc = wit[(a, a)]
            diags.append(ConcurrencyDiagnostic(
                code="lock-order-cycle", severity=Severity.ERROR,
                message=f"self-deadlock: {desc}",
                var=a, site=[f"{path}:{line}"],
                path=path, qual=a, detail=a, line=line))

    # Tarjan SCC (iterative)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []

    def strongconnect(root: str):
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                elif w in on:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                if len(comp) > 1:
                    sccs.append(sorted(comp))

    for node in sorted(adj):
        if node not in index:
            strongconnect(node)

    for comp in sccs:
        edges = [(a, b) for a in comp for b in adj.get(a, ())
                 if b in comp and a != b]
        witness = "; ".join(
            wit[(a, b)][2] for a, b in sorted(edges)[:4]
            if (a, b) in wit)
        path, line, _ = wit[sorted(edges)[0]] if edges else ("", 0, "")
        diags.append(ConcurrencyDiagnostic(
            code="lock-order-cycle", severity=Severity.ERROR,
            message=(f"lock-order cycle between "
                     f"{{{', '.join(comp)}}}: {witness} — a consistent "
                     f"acquisition order (or lock-free handoff) is "
                     f"required"),
            var=",".join(comp), site=[f"{path}:{line}"],
            path=path, qual=comp[0], detail=",".join(comp), line=line))
    return diags


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def _extract_module(src: str, path: str) -> List[_ClassModel]:
    if _SKIP_FILE_RE.search("\n".join(src.splitlines()[:10])):
        return []
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    lines = src.splitlines()
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            cm = _extract_class(node, path, lines)
            _compute_entry_held(cm)
            out.append(cm)
    return out


def _analyze_corpus(modules: List[Tuple[str, str]]
                    ) -> List[ConcurrencyDiagnostic]:
    """modules: [(source, repo-relative path)]."""
    corpus: Dict[str, _ClassModel] = {}
    per_file: Dict[str, List[_ClassModel]] = {}
    sups: Dict[str, _Suppressions] = {}
    for src, path in modules:
        cms = _extract_module(src, path)
        per_file.setdefault(path, []).extend(cms)
        sups[path] = _Suppressions(src.splitlines())
        for cm in cms:
            corpus.setdefault(cm.name, cm)
    mb = _may_block(corpus)
    diags: List[ConcurrencyDiagnostic] = []
    for path, cms in sorted(per_file.items()):
        sup = sups[path]
        for cm in cms:
            diags.extend(_check_guards(cm, sup))
            diags.extend(_check_blocking(cm, corpus, mb, sup))
    diags.extend(_check_cycles(corpus))
    diags.sort(key=lambda d: (-int(d.severity), d.path, d.line, d.code))
    return diags


def analyze_source(src: str, filename: str = "<string>"
                   ) -> List[ConcurrencyDiagnostic]:
    """Analyze one module's source text (fixture entry point)."""
    return _analyze_corpus([(src, filename)])


def analyze_paths(paths: Sequence[str], root: Optional[str] = None
                  ) -> List[ConcurrencyDiagnostic]:
    """Analyze a set of .py files together (one corpus: cross-class
    edges resolve across files). `root` anchors repo-relative paths."""
    modules = []
    for p in paths:
        try:
            with open(p, encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        rel = os.path.relpath(p, root) if root else p
        modules.append((src, rel))
    return _analyze_corpus(modules)


def analyze_package(pkg_dir: str, root: Optional[str] = None
                    ) -> List[ConcurrencyDiagnostic]:
    """Analyze every .py under a directory tree."""
    paths = []
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in sorted(dirnames)
                       if d not in ("__pycache__",)]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                paths.append(os.path.join(dirpath, fn))
    return analyze_paths(paths, root=root or os.path.dirname(
        os.path.abspath(pkg_dir)))
