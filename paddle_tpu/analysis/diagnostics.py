"""Severity-ranked diagnostics for the static analysis layer.

Capability parity with the reference's build-time error surface: the C++
InferShape/OpDesc checks raise EnforceNotMet with an attached call stack
(reference: paddle/fluid/platform/enforce.h, operator.cc's
`op_callstack` attr); the inference analyzer emits ordered findings per
pass (reference: paddle/fluid/inference/analysis/analyzer.cc). Here every
finding is a `Diagnostic` record carrying (block idx, op idx, op type,
var) provenance plus the op's trimmed creation traceback captured by
`Operator.__init__` (core/ir.py), so "op 37 has a bad input" points back
at the layers-DSL line that built op 37 — not at the XLA lowering that
tripped over it 40k steps later.

The TPU-specific lints live here too (float64 use, dead ops relative to
fetch targets, feed-shape recompilation hazards): they are properties of
the IR that only *matter* on this backend, not structural errors.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from ..core import ir, registry


class Severity(enum.IntEnum):
    """Ranked: higher = more severe (sort descending for display)."""

    INFO = 1
    WARNING = 2
    ERROR = 3

    def __str__(self):
        return self.name


@dataclass
class Diagnostic:
    """One finding, with enough provenance to act on it."""

    code: str                 # stable kebab-case id, e.g. "undefined-input"
    severity: Severity
    message: str
    block_idx: int = 0
    op_idx: Optional[int] = None
    op_type: Optional[str] = None
    var: Optional[str] = None
    site: Optional[List[str]] = None  # trimmed creation traceback (user frames)

    def format(self, show_site: bool = True) -> str:
        where = f"block {self.block_idx}"
        if self.op_idx is not None:
            where += f" op {self.op_idx}"
        if self.op_type:
            where += f" ({self.op_type})"
        out = f"{self.severity}: [{self.code}] {where}: {self.message}"
        if show_site and self.site:
            out += "".join(f"\n    built at {s}" for s in self.site)
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {"code": self.code, "severity": self.severity.name,
                "message": self.message, "block_idx": self.block_idx,
                "op_idx": self.op_idx, "op_type": self.op_type,
                "var": self.var, "site": self.site}


def diag_for_op(code: str, severity: Severity, message: str,
                block: ir.Block, op_idx: Optional[int] = None,
                op: Optional[ir.Operator] = None,
                var: Optional[str] = None) -> Diagnostic:
    """Build a Diagnostic with provenance pulled off the op itself."""
    return Diagnostic(
        code=code, severity=severity, message=message, block_idx=block.idx,
        op_idx=op_idx, op_type=op.type if op is not None else None, var=var,
        site=getattr(op, "_creation_site", None))


def sort_diagnostics(diags: Sequence[Diagnostic]) -> List[Diagnostic]:
    """Most severe first; program order within a severity."""
    return sorted(diags, key=lambda d: (-int(d.severity), d.block_idx,
                                        d.op_idx if d.op_idx is not None else -1))


def has_errors(diags: Sequence[Diagnostic]) -> bool:
    return any(d.severity == Severity.ERROR for d in diags)


def format_diagnostics(diags: Sequence[Diagnostic],
                       show_site: bool = True) -> str:
    return "\n".join(d.format(show_site=show_site)
                     for d in sort_diagnostics(diags))


class ProgramVerificationError(ValueError):
    """Raised by validate="error" surfaces; carries the findings."""

    def __init__(self, diags: Sequence[Diagnostic], context: str = "program"):
        self.diagnostics = list(diags)
        errors = [d for d in self.diagnostics if d.severity == Severity.ERROR]
        super().__init__(
            f"{context} failed static verification with {len(errors)} "
            f"error(s):\n{format_diagnostics(self.diagnostics)}")


# ---------------------------------------------------------------------------
# TPU-specific lints (advisory WARNINGs — except comm-float64, which is a
# contract violation at the wire boundary and rates an ERROR)
# ---------------------------------------------------------------------------

def lint_program(program: ir.Program,
                 fetch_targets: Optional[Sequence[str]] = None
                 ) -> List[Diagnostic]:
    """Backend-fit lints over a structurally valid program."""
    diags: List[Diagnostic] = []
    diags += _lint_float64(program)
    diags += _lint_comm_float64(program)
    diags += _lint_feed_shape_hazards(program)
    diags += _lint_static_inference_feeds(program)
    if fetch_targets:
        diags += _lint_dead_ops(program, list(fetch_targets))
        diags += lint_dead_fetch_targets(program, list(fetch_targets))
    return diags


def _lint_float64(program: ir.Program) -> List[Diagnostic]:
    """float64 has no native TPU support: XLA emulates it in software at
    a large slowdown (and some ops refuse outright). The reference ran
    f64 kernels natively on CUDA, so ported configs carry it silently."""
    diags = []
    for blk in program.blocks:
        flagged = set()
        for v in blk.vars.values():
            if v.dtype == "float64":
                diags.append(Diagnostic(
                    "float64-on-tpu", Severity.WARNING,
                    f"variable {v.name!r} is float64: TPUs have no native "
                    f"f64 (software emulation, large slowdown) — use "
                    f"float32 or bfloat16", block_idx=blk.idx, var=v.name))
                flagged.add(v.name)
        for i, op in enumerate(blk.ops):
            dt = op.attrs.get("dtype")
            if isinstance(dt, str) and dt in ("float64", "fp64", "double") \
                    and not (set(op.output_arg_names) & flagged):
                diags.append(diag_for_op(
                    "float64-on-tpu", Severity.WARNING,
                    f"attr dtype={dt!r}: TPUs have no native f64",
                    blk, i, op))
    return diags


def _lint_comm_float64(program: ir.Program) -> List[Diagnostic]:
    """fluid-wire extension of the float64 lint to the WIRE contract: a
    gradient reaching a quantized communication boundary (a
    `comm_quant_dequant` op — wire/graph.py) with dtype float64 is an
    ERROR, not advice. The wire codecs are float32-only (wire/codec.py
    refuses f64 at runtime with the same message), an f64 gradient at an
    int8/bf16 boundary means the program silently planned to throw away
    ~45 bits while paying f64 compute upstream — a config mistake, never
    an intentional trade."""
    diags = []
    for blk in program.blocks:
        for i, op in enumerate(blk.ops):
            if op.type != "comm_quant_dequant":
                continue
            for slot in ("Grad", "Residual"):
                for name in op.input(slot):
                    v = blk._find_var_recursive(name)
                    if v is not None and v.dtype == "float64":
                        diags.append(diag_for_op(
                            "comm-float64", Severity.ERROR,
                            f"{slot.lower()} var {name!r} is float64 at a "
                            f"quantized communication boundary (codec "
                            f"{op.attrs.get('codec', 'int8')!r}): the wire "
                            f"contract is float32 — cast the model to "
                            f"float32, or drop comm_quant for this "
                            f"program", blk, i, op, var=name))
    return diags


def _lint_feed_shape_hazards(program: ir.Program) -> List[Diagnostic]:
    """The executor compiles one XLA program per concrete feed shape, so
    dynamic (-1) dims beyond the batch dim recompile the step on every
    new extent. A contiguous LEADING run of -1s (batch + time levels) is
    the documented padded-sequence feed contract — DataFeeder pads and
    callers bucket — so it rates an INFO note. A -1 sitting AFTER a
    concrete dim has no such contract: that shape recompiles per batch
    and is almost always a declaration mistake -> WARNING. LoD
    (lod_level>0) inputs are the sequence contract by definition."""
    diags = []
    for blk in program.blocks:
        for v in blk.vars.values():
            if not v.is_data or v.lod_level > 0 or -1 not in v.shape[1:]:
                continue
            lead = 0
            while lead < len(v.shape) and v.shape[lead] == -1:
                lead += 1
            trailing_dynamic = any(d == -1 for d in v.shape[lead:])
            diags.append(Diagnostic(
                "feed-shape-recompile",
                Severity.WARNING if trailing_dynamic else Severity.INFO,
                f"data var {v.name!r} shape {tuple(v.shape)} has a dynamic "
                f"dim beyond the batch dim: each distinct feed shape "
                f"compiles a separate XLA program (jit-cache churn) — pad "
                f"to a fixed extent or bucket feed lengths",
                block_idx=blk.idx, var=v.name))
    return diags


def _lint_dead_ops(program: ir.Program,
                   fetch_targets: List[str]) -> List[Diagnostic]:
    """Ops whose outputs never reach a fetch target, a persistable write
    (parameter/accumulator updates ARE the point of a training step), or a
    side-effecting op. Dead ops still trace, compile, and mostly get DCE'd
    by XLA — but they inflate compile time and hide builder bugs (a loss
    wired to the wrong var fetches fine and trains nothing)."""
    diags = []
    blk = program.global_block()
    needed = set(fetch_targets)
    live = [False] * len(blk.ops)
    for i in range(len(blk.ops) - 1, -1, -1):
        op = blk.ops[i]
        out_names = [n for n in op.output_arg_names
                     if n != registry.EMPTY_VAR]
        side_effecting = (op.type in _SIDE_EFFECT_OPS
                          or bool(ir.sub_block_indices(op)))
        writes_persistable = any(
            (v := blk._find_var_recursive(n)) is not None and v.persistable
            for n in out_names)
        # an op is also live if a LIVE op downstream needs the @SEQLEN
        # companion of one of its outputs (runtime seqlen propagation
        # materializes companions without an explicit producing op)
        companion_hit = any(n + ir.SEQLEN_SUFFIX in needed
                            for n in out_names)
        if side_effecting or writes_persistable or companion_hit \
                or (needed & set(out_names)):
            live[i] = True
            ins = {n for n in op.input_arg_names if n != registry.EMPTY_VAR}
            for si in ir.sub_block_indices(op):
                ins |= set(ir.external_reads(program, si))
            needed |= ins
            needed |= {n + ir.SEQLEN_SUFFIX for n in ins}
    for i, op in enumerate(blk.ops):
        if not live[i]:
            diags.append(diag_for_op(
                "dead-op", Severity.WARNING,
                f"op never reaches a fetch target "
                f"{sorted(fetch_targets)} or a persistable write — "
                f"mis-wired graph or leftover build code", blk, i, op))
    return diags


_SIDE_EFFECT_OPS = frozenset({"feed", "fetch", "listen_and_serv", "print",
                              "py_reader", "read", "send", "recv"})


def _lint_static_inference_feeds(program: ir.Program) -> List[Diagnostic]:
    """Inference programs whose feed vars declare FULLY static shapes
    (batch dim included) lock the request path to exactly one shape: a
    shape-bucketing server (serve/) cannot pad a 3-row request onto an
    8-row rung, and every client must submit the declared batch size
    exactly. Legal — one warm compile serves all traffic — but it
    defeats micro-batch coalescing, so it rates an INFO note on the
    inference slice only (training programs routinely pin the batch)."""
    if not getattr(program, "_is_inference", False):
        return []
    diags = []
    blk = program.global_block()
    for v in blk.vars.values():
        if v.is_data and v.shape and -1 not in v.shape:
            diags.append(Diagnostic(
                "static-inference-feed", Severity.INFO,
                f"feed var {v.name!r} declares the fully static shape "
                f"{tuple(v.shape)}: every request must match it exactly, "
                f"so a shape-bucketing server cannot coalesce or pad "
                f"mixed batch sizes — declare the batch dim as -1 to "
                f"enable bucketing", block_idx=blk.idx, var=v.name))
    return diags


def lint_dead_fetch_targets(program: ir.Program,
                            fetch_targets: Sequence[str]
                            ) -> List[Diagnostic]:
    """Fetch targets NOTHING in the program produces: no op writes them
    and they are neither feeds nor persistables, so fetching reads an
    undefined value. The classic way to get one is `save_inference_model`
    pruning: a target wired to the training-only graph survives in the
    vars table while its producing op is stripped by the for_test clone —
    the saved model then loads fine and serves garbage."""
    blk = program.global_block()
    produced = set()
    for op in blk.ops:
        for n in op.output_arg_names:
            if n == registry.EMPTY_VAR:
                continue
            produced.add(n)
            # runtime seqlen propagation materializes @SEQLEN companions
            # without an explicit producing op
            produced.add(n + ir.SEQLEN_SUFFIX)
            produced.add(n + ir.SEQLEN_SUFFIX + ".1")
    diags = []
    for t in fetch_targets:
        if t in produced:
            continue
        v = blk._find_var_recursive(t)
        if v is None or v.is_data or v.persistable:
            # nonexistent targets are the verifier's ERROR; feeds and
            # persistables have well-defined values without a producer
            continue
        diags.append(Diagnostic(
            "dead-fetch-target", Severity.WARNING,
            f"fetch target {t!r} is produced by no op in this program "
            f"and is neither a feed nor persistable — fetching it reads "
            f"an undefined value (was its producer pruned away by "
            f"save_inference_model's for_test clone?)",
            block_idx=blk.idx, var=t))
    return diags
