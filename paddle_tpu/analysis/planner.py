"""fluid-planner: cost-model-driven auto-sharding and auto-tuning.

The repo grew three hand-tuned performance surfaces — the dp×mp×sp mesh
passed to the parallel path, the serving bucket ladder, and the XLA flag
sweep's probe order — and a per-op cost model none of them consumed.
This module closes that loop (ROADMAP item 4; GDP in PAPERS.md grounds
deriving placement from the dataflow graph instead of hand-picking):

1. `estimate_step_time` extends the per-op FLOPs/bytes table
   (`cost_model.estimate_cost`) to a per-op TIME estimate — a roofline
   `max(flops / achievable_flops, bytes / achievable_bw)` per op, summed,
   plus a calibrated host/dispatch floor;
2. `plan_meshes` searches the dp×mp×sp factorizations of a chip count
   for a given program: per candidate it models the communication
   (bytes moved per gradient all-reduce / Megatron activation all-reduce
   / ring-attention collective-permute — the same collective kinds the
   multichip dryrun's inventory records), the per-device peak HBM
   (rejecting OOM candidates via `estimate_peak_hbm`), and returns a
   ranked `PlanReport` with predicted step time, MFU and
   bytes-on-the-wire. `parallel.mesh.auto_mesh` rides this;
3. `flag_family_priors` ranks XLA compiler-flag FAMILIES by the
   program's cost profile so `tools/xla_flag_sweep.py` probes the
   likely-winning family first (measured on this chip: the scoped-VMEM
   budget is worth +9% on the matmul-dominant transformer and −7% on
   the bandwidth-bound ResNet — exactly the split the priors encode);
4. `optimal_rungs` is the padding-waste-minimizing ladder solver behind
   `serve.BucketLadder.from_trace`.

Honesty contract (docs/PLANNER.md has the full argument + calibration):
every number here is a MODEL. The roofline is calibrated against the
recorded bench rounds (predicted/measured MFU band pinned in
tests/test_planner.py), the mesh ranking against the recorded MULTICHIP
dryruns and a measured 4-mesh table on the 8-device virtual-CPU rig,
and the flag priors against the recorded phase-1 sweep. Predictions
rank candidates; they do not replace measurement.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import ir
from . import cost_model
from .cost_model import CostReport, estimate_cost, estimate_peak_hbm

_MATMUL_FAMILY = set(cost_model._MATMUL_LIKE) | {
    t + "_grad" for t in cost_model._MATMUL_LIKE} | {"fused_attention",
                                                     "fused_attention_grad"}
_CONV_FAMILY = {"conv2d", "depthwise_conv2d", "conv2d_grad",
                "depthwise_conv2d_grad"}
_REDUCE_BCAST_FAMILY = {"softmax", "log_softmax", "layer_norm",
                        "batch_norm", "softmax_with_cross_entropy"}


class HardwareSpec:
    """The calibrated machine model one plan is computed against.

    All rates are *achievable*, not datasheet: `peak_flops` is the
    bench-measured matmul peak, and the per-family efficiencies absorb
    what a real compiled step loses to fusion boundaries, layout ops and
    sub-tile shapes (docs/PLANNER.md §calibration has the derivation
    from the recorded BENCH rounds).

    - ``peak_flops``       measured matmul peak, FLOP/s
    - ``hbm_bw``           HBM bandwidth, B/s
    - ``hbm_bytes``        per-device memory budget (OOM gate)
    - ``ici_bw``           per-link interconnect bandwidth, B/s
    - ``launch_us``        per-collective launch/latency cost
    - ``dispatch_us``      host dispatch floor added to every step
    - ``matmul_eff``       achievable fraction of peak for MXU ops
    - ``vector_eff``       same for elementwise/reduction ops
    - ``hbm_traffic_fraction``  fraction of the static per-op bytes that
                           actually pays HBM (fusion keeps the rest in
                           registers/VMEM; static per-op byte sums count
                           every producer/consumer edge)
    - ``min_tile``         matrix-unit tile edge; per-device shards
                           below it waste MXU lanes proportionally
    - ``parallel_scaling`` how much of the ideal 1/N compute split the
                           rig realizes: effective shards = N**this.
                           1.0 = real chips; 0.0 = the virtual-device
                           CPU rig, whose 8 "devices" timeshare one
                           core (compute never shrinks, collectives are
                           pure added work)
    """

    __slots__ = ("name", "peak_flops", "hbm_bw", "hbm_bytes", "ici_bw",
                 "launch_us", "dispatch_us", "matmul_eff", "vector_eff",
                 "hbm_traffic_fraction", "min_tile", "parallel_scaling")

    def __init__(self, name, peak_flops, hbm_bw, hbm_bytes, ici_bw,
                 launch_us, dispatch_us, matmul_eff, vector_eff,
                 hbm_traffic_fraction, min_tile, parallel_scaling=1.0):
        self.name = name
        self.peak_flops = float(peak_flops)
        self.hbm_bw = float(hbm_bw)
        self.hbm_bytes = float(hbm_bytes)
        self.ici_bw = float(ici_bw)
        self.launch_us = float(launch_us)
        self.dispatch_us = float(dispatch_us)
        self.matmul_eff = float(matmul_eff)
        self.vector_eff = float(vector_eff)
        self.hbm_traffic_fraction = float(hbm_traffic_fraction)
        self.min_tile = int(min_tile)
        self.parallel_scaling = float(parallel_scaling)

    def replace(self, **kw) -> "HardwareSpec":
        vals = {s: getattr(self, s) for s in self.__slots__}
        vals.update(kw)
        return HardwareSpec(**vals)

    def as_dict(self) -> dict:
        return {s: getattr(self, s) for s in self.__slots__}

    def __repr__(self):
        return (f"HardwareSpec({self.name}, "
                f"{self.peak_flops / 1e12:.1f} TFLOP/s, "
                f"{self.hbm_bw / 1e12:.2f} TB/s HBM, "
                f"{self.hbm_bytes / 1e9:.1f} GB)")


# The bench chip, calibrated against the recorded rounds: peak is the
# BENCH_r04 measured 191.5 TFLOP/s bf16; ResNet-50 sustains ~1 TB/s HBM
# at its ~27% roofline (docs/PERF.md); 15.75 GB HBM per chip; matmul_eff
# + hbm_traffic_fraction are fit so the full-size transformer's
# predicted MFU lands on the recorded 0.46-0.51 band and ResNet stays
# bandwidth-bound (tests/test_planner.py pins the band).
TPU_CHIP = HardwareSpec(
    name="tpu-dev-chip", peak_flops=191.5e12, hbm_bw=1.23e12,
    hbm_bytes=15.75e9, ici_bw=9.0e10, launch_us=2.0, dispatch_us=30.0,
    matmul_eff=0.72, vector_eff=0.25, hbm_traffic_fraction=0.40,
    min_tile=128, parallel_scaling=1.0)

# The 8-virtual-device 1-core CPU rig the test suite (and the multichip
# dryrun) runs on: every "device" timeshares one core, so collectives
# are pure overhead — a large per-collective launch cost and a thin
# bandwidth. Absolute times are rough; the RANKING is what the measured
# 4-mesh table in docs/PLANNER.md validates.
CPU_REHEARSAL = HardwareSpec(
    name="cpu-rehearsal-8dev", peak_flops=3.5e9, hbm_bw=12.0e9,
    hbm_bytes=64e9, ici_bw=2.0e9, launch_us=250.0, dispatch_us=400.0,
    matmul_eff=1.0, vector_eff=1.0, hbm_traffic_fraction=1.0,
    min_tile=32, parallel_scaling=0.0)


def detect_hardware() -> HardwareSpec:
    """CPU backends get the rehearsal profile, anything else the chip."""
    try:
        import jax
        platform = jax.devices()[0].platform
    except Exception:
        platform = "cpu"
    return CPU_REHEARSAL if platform == "cpu" else TPU_CHIP


# ---------------------------------------------------------------------------
# roofline time model
# ---------------------------------------------------------------------------

def _op_eff(op_type: str, hw: HardwareSpec) -> float:
    return hw.matmul_eff if op_type in _MATMUL_FAMILY else hw.vector_eff


def estimate_step_time(report: CostReport, hw: HardwareSpec,
                       n_shards: int = 1, shard_eff: float = 1.0) -> dict:
    """Roofline step-time estimate: per op,
    max(flops / (peak·eff), hbm_fraction·bytes / hbm_bw), summed, plus
    the dispatch floor. `n_shards` divides every op's work (the ideal
    dp·mp·sp split — feasibility is the caller's job); `shard_eff`
    further derates the compute term for sub-tile shards."""
    n = max(int(n_shards), 1)
    se = min(max(float(shard_eff), 1e-3), 1.0)
    t_flops_total = t_bytes_total = t_sum = 0.0
    bound_flops = 0
    for op in report.ops:
        t_f = op.flops / n / (hw.peak_flops * _op_eff(op.op_type, hw) * se)
        t_b = (hw.hbm_traffic_fraction * op.bytes / n) / hw.hbm_bw
        t_flops_total += t_f
        t_bytes_total += t_b
        if t_f >= t_b:
            bound_flops += 1
        t_sum += max(t_f, t_b)
    return {
        "compute_s": t_sum,
        "dispatch_s": hw.dispatch_us * 1e-6,
        "step_s": t_sum + hw.dispatch_us * 1e-6,
        "flops_bound_ops": bound_flops,
        "bytes_bound_ops": len(report.ops) - bound_flops,
        "sum_flops_s": t_flops_total,
        "sum_bytes_s": t_bytes_total,
    }


# ---------------------------------------------------------------------------
# program introspection for the mesh search
# ---------------------------------------------------------------------------

class _ProgramProfile:
    """Everything the mesh search needs to know about one program,
    derived once: batch/seq extents, mp-shardable params, row-parallel
    matmul outputs (the Megatron activation-AR sites), attention ops
    and their K/V payloads, gradient tensor count."""

    def __init__(self, program: ir.Program,
                 feed_shapes: Dict[str, Sequence[int]],
                 default_dim: Optional[int]):
        self.report = estimate_cost(program, feed_shapes, default_dim)
        self.hbm = estimate_peak_hbm(program, feed_shapes, default_dim)
        env = cost_model.shape_env(program, feed_shapes, default_dim)
        blk = program.global_block()

        shapes = [tuple(int(d) for d in s) for s in feed_shapes.values()]
        self.batch = int(shapes[0][0]) if shapes and len(shapes[0]) else 1
        self.seq = 0
        for s in shapes:
            if len(s) >= 2 and int(s[1]) > 1:
                self.seq = int(s[1])
                break

        # mp-shardable params: ParamAttr.sharding tuples naming 'mp'
        # (the same annotations ParallelExecutor._sharding_for_state
        # consumes). Row-parallel = 'mp' on axis 0 (output needs the
        # Megatron all-reduce); column-parallel = 'mp' elsewhere.
        self.mp_params: List[Tuple[str, Tuple[int, ...], int]] = []
        self.mp_param_bytes = 0.0
        row_parallel_names = set()
        param_names = set()
        for v in blk.vars.values():
            if not v.persistable:
                continue
            param_names.add(v.name)
            spec = getattr(v, "sharding", None)
            if not spec or "mp" not in tuple(spec):
                continue
            sd = env.get(v.name)
            shape = sd[0] if sd else tuple(
                int(d) for d in v.shape if int(d) != -1)
            axis = tuple(spec).index("mp")
            if axis < len(shape):
                self.mp_params.append((v.name, shape, axis))
                self.mp_param_bytes += cost_model._nbytes(
                    (shape, v.dtype or "float32"))
                if axis == 0:
                    row_parallel_names.add(v.name)

        # activation-AR payload: outputs of FORWARD ops consuming a
        # row-parallel param (Megatron: the partial products must be
        # summed over mp). Grad ops also read the param but their AR is
        # the explicit fwd+bwd 2x in the comm model, and optimizer ops
        # (Param+Grad slots) update state that never all-reduces —
        # counting either would triple the mp comm estimate.
        from ..core.registry import GRAD_OP_SUFFIX
        self.rowpar_sites = 0
        self.rowpar_out_bytes = 0.0
        self.attn_ops = 0
        self.attn_kv_bytes = 0.0
        self.attn_has_dropout = False
        for op in blk.ops:
            ins = set(op.input_arg_names)
            is_fwd_consumer = (
                not op.type.endswith(GRAD_OP_SUFFIX)
                and not ("Param" in op.inputs and "Grad" in op.inputs))
            if is_fwd_consumer and ins & row_parallel_names:
                self.rowpar_sites += 1
                self.rowpar_out_bytes += sum(
                    cost_model._nbytes(env.get(n))
                    for n in op.output_arg_names)
            if op.type == "fused_attention":
                self.attn_ops += 1
                for slot in ("K", "V"):
                    names = op.inputs.get(slot) or ()
                    self.attn_kv_bytes += sum(
                        cost_model._nbytes(env.get(n)) for n in names)
                if (float(op.attrs.get("dropout_rate", 0.0) or 0.0) > 0.0
                        and not op.attrs.get("is_test", False)):
                    self.attn_has_dropout = True

        # gradient tensors the dp all-reduce moves (one logical AR each;
        # XLA fuses some — this is the launch-cost model, not HLO truth).
        # Their byte total is the dp payload; estimate_peak_hbm's
        # grad_bytes also counts ACTIVATION grads, which never cross the
        # wire and shard over dp·sp like their activations.
        self.n_grad_tensors = 0
        self.param_grad_bytes = 0.0
        for v in blk.vars.values():
            if v.persistable or ir.GRAD_SUFFIX not in v.name:
                continue
            if v.name.split(ir.GRAD_SUFFIX)[0] not in param_names:
                continue
            self.n_grad_tensors += 1
            sd = env.get(v.name)
            if sd is None and v.shape != ():
                sd = (tuple(max(int(d), 1) for d in v.shape),
                      v.dtype or "float32")
            self.param_grad_bytes += cost_model._nbytes(sd)

        # flops shares the sub-tile derating scales with: mp shards the
        # matmul family, sp (ring attention) shards only the attention
        profile = cost_profile(self.report)
        self.matmul_share = profile["matmul_share"]
        by = self.report.by_type()
        self.attn_share = sum(
            a["flops"] for t, a in by.items()
            if t in ("fused_attention", "fused_attention_grad")) \
            / (self.report.total_flops or 1.0)


# ---------------------------------------------------------------------------
# mesh candidates
# ---------------------------------------------------------------------------

class MeshPlan:
    """One dp×mp×sp candidate with its predictions (or rejection)."""

    __slots__ = ("dp", "mp", "sp", "feasible", "reason", "t_compute_s",
                 "t_comm_s", "t_step_s", "mfu", "peak_hbm_bytes",
                 "wire_bytes", "collectives")

    def __init__(self, dp, mp, sp):
        self.dp, self.mp, self.sp = int(dp), int(mp), int(sp)
        self.feasible = True
        self.reason = ""
        self.t_compute_s = self.t_comm_s = self.t_step_s = 0.0
        self.mfu = 0.0
        self.peak_hbm_bytes = 0.0
        self.wire_bytes = 0.0
        self.collectives: Dict[str, int] = {}

    @property
    def n_devices(self) -> int:
        return self.dp * self.mp * self.sp

    @property
    def axes(self) -> Tuple[int, int, int]:
        return (self.dp, self.mp, self.sp)

    def label(self) -> str:
        return f"dp{self.dp}xmp{self.mp}xsp{self.sp}"

    def as_dict(self) -> dict:
        return {"dp": self.dp, "mp": self.mp, "sp": self.sp,
                "feasible": self.feasible, "reason": self.reason,
                "step_time_us": round(self.t_step_s * 1e6, 2),
                "compute_us": round(self.t_compute_s * 1e6, 2),
                "comm_us": round(self.t_comm_s * 1e6, 2),
                "mfu": round(self.mfu, 4),
                "peak_hbm_bytes": round(self.peak_hbm_bytes),
                "wire_bytes_per_step": round(self.wire_bytes),
                "collectives": dict(self.collectives)}


def enumerate_meshes(n_devices: int) -> List[Tuple[int, int, int]]:
    """All (dp, mp, sp) with dp·mp·sp == n_devices."""
    out = []
    n = int(n_devices)
    for dp in range(1, n + 1):
        if n % dp:
            continue
        rem = n // dp
        for mp in range(1, rem + 1):
            if rem % mp:
                continue
            out.append((dp, mp, rem // mp))
    return out


class PlanReport:
    """Ranked mesh candidates for one (program, chip count): feasible
    candidates first, fastest predicted step time first; rejected
    candidates follow, each naming its reason."""

    def __init__(self, candidates: List[MeshPlan], n_devices: int,
                 hw: HardwareSpec, report: CostReport):
        feas = sorted([c for c in candidates if c.feasible],
                      key=lambda c: c.t_step_s)
        # rejected: memory-gated candidates first (they carry full
        # predictions and are the informative ones when NOTHING fits —
        # the CLI reports candidates[0] as "top"), structural rejections
        # after, both fastest-predicted first
        rej = sorted([c for c in candidates if not c.feasible],
                     key=lambda c: (0 if "HBM" in c.reason else 1,
                                    c.t_step_s or float("inf")))
        self.candidates = feas + rej
        self.n_devices = int(n_devices)
        self.hw = hw
        self.cost = report

    @property
    def best(self) -> Optional[MeshPlan]:
        return self.candidates[0] if (self.candidates
                                      and self.candidates[0].feasible) \
            else None

    def predicted(self, dp: int, mp: int = 1, sp: int = 1
                  ) -> Optional[MeshPlan]:
        for c in self.candidates:
            if c.axes == (int(dp), int(mp), int(sp)):
                return c
        return None

    def as_dict(self, top_k: int = 10) -> dict:
        best = self.best
        return {
            "n_devices": self.n_devices,
            "hardware": self.hw.as_dict(),
            "total_flops": self.cost.total_flops,
            "total_bytes": self.cost.total_bytes,
            "best": best.as_dict() if best else None,
            "candidates": [c.as_dict() for c in self.candidates[:top_k]],
            "rejected": sum(1 for c in self.candidates if not c.feasible),
        }

    def table(self, k: int = 12) -> str:
        lines = [f"{'mesh':<16} {'step':>10} {'MFU':>6} {'peak HBM':>10} "
                 f"{'wire/step':>10}  {'comm':>9}  collectives"]
        for c in self.candidates[:k]:
            if not c.feasible:
                lines.append(f"{c.label():<16} {'—':>10} {'—':>6} "
                             f"{'—':>10} {'—':>10}  {'—':>9}  "
                             f"REJECTED: {c.reason}")
                continue
            coll = ",".join(f"{k_}:{v}" for k_, v in
                            sorted(c.collectives.items())) or "none"
            lines.append(
                f"{c.label():<16} {c.t_step_s * 1e3:>8.3f}ms "
                f"{c.mfu:>6.1%} {c.peak_hbm_bytes / 1e9:>8.2f}GB "
                f"{c.wire_bytes / 1e6:>8.2f}MB  "
                f"{c.t_comm_s * 1e3:>7.3f}ms  {coll}")
        lines.append(f"[{self.hw.name}: {self.hw.peak_flops / 1e12:.1f} "
                     f"TFLOP/s peak, {self.hw.hbm_bytes / 1e9:.1f} GB "
                     f"budget, {self.n_devices} device(s)]")
        return "\n".join(lines)


def _shard_penalty(prof: _ProgramProfile, mp: int, sp: int,
                   hw: HardwareSpec, compute_s: float) -> float:
    """Sub-tile derating, as ADDED compute time: per-device extents
    below the matrix-unit tile waste lanes proportionally, but only for
    the ops that axis actually shards — mp derates the matmul family,
    sp (ring attention) derates only the attention ops."""
    extra = 0.0
    if mp > 1 and prof.mp_params:
        smallest = min(shape[axis] // mp
                       for _, shape, axis in prof.mp_params)
        eff = min(1.0, max(max(smallest, 1) / hw.min_tile, 1e-2))
        extra += compute_s * prof.matmul_share * (1.0 / eff - 1.0)
    if sp > 1 and prof.seq:
        eff = min(1.0, max((prof.seq / sp) / hw.min_tile, 1e-2))
        extra += compute_s * prof.attn_share * (1.0 / eff - 1.0)
    return extra


# fraction of the static activation(+grad) byte sum resident at the real
# peak: XLA's liveness/reuse keeps far less than the every-intermediate
# sum alive. 0.25 is calibrated so every config the bench actually ran
# on the 15.75 GB chip plans feasible while the known-OOM seq-8192
# unfused config rejects (docs/PLANNER.md has the table).
LIVE_FRACTION = 0.25


def _evaluate(cand: MeshPlan, prof: _ProgramProfile,
              hw: HardwareSpec, live_fraction: float = LIVE_FRACTION
              ) -> None:
    dp, mp, sp = cand.dp, cand.mp, cand.sp
    n = cand.n_devices

    # -- feasibility gates -------------------------------------------------
    if dp > 1 and prof.batch % dp:
        cand.feasible = False
        cand.reason = f"batch {prof.batch} not divisible by dp={dp}"
        return
    if mp > 1:
        if not prof.mp_params:
            cand.feasible = False
            cand.reason = "program has no mp-shardable params"
            return
        bad = [(nm, shape[axis]) for nm, shape, axis in prof.mp_params
               if shape[axis] % mp]
        if bad:
            cand.feasible = False
            cand.reason = (f"param {bad[0][0]!r} dim {bad[0][1]} not "
                           f"divisible by mp={mp}")
            return
    if sp > 1:
        if not prof.attn_ops:
            cand.feasible = False
            cand.reason = "no fused_attention op (ring attention needs one)"
            return
        if prof.attn_has_dropout:
            cand.feasible = False
            cand.reason = "attention dropout active (sp requires 0)"
            return
        if not prof.seq or prof.seq % sp:
            cand.feasible = False
            cand.reason = f"seq {prof.seq} not divisible by sp={sp}"
            return

    # -- compute (roofline over the rig's realizable split) ----------------
    rt = estimate_step_time(prof.report, hw,
                            n_shards=n ** hw.parallel_scaling)
    cand.t_compute_s = rt["compute_s"] + _shard_penalty(
        prof, mp, sp, hw, rt["compute_s"])

    # -- communication -----------------------------------------------------
    t_comm = 0.0
    wire = 0.0
    coll: Dict[str, int] = {}
    mp_frac = (min(prof.mp_param_bytes / prof.hbm["param_bytes"], 1.0)
               if prof.hbm["param_bytes"] else 0.0)
    shard_param = mp_frac / mp + (1 - mp_frac)
    if dp > 1:
        # ring all-reduce of the PARAM gradients: 2(dp-1)/dp of the
        # payload crosses each device's links; mp-sharded params' grads
        # carry only their 1/mp shard
        payload = prof.param_grad_bytes * shard_param
        b = 2.0 * (dp - 1) / dp * payload
        wire += b
        t_comm += b / hw.ici_bw + hw.launch_us * 1e-6 * prof.n_grad_tensors
        coll["all-reduce"] = coll.get("all-reduce", 0) + prof.n_grad_tensors
    if mp > 1:
        # Megatron activation all-reduce after every row-parallel
        # matmul, forward + backward; payload is the per-device
        # activation slice
        payload = 2.0 * prof.rowpar_out_bytes / max(dp * sp, 1)
        b = 2.0 * (mp - 1) / mp * payload
        wire += b
        n_ar = 2 * prof.rowpar_sites
        t_comm += b / hw.ici_bw + hw.launch_us * 1e-6 * n_ar
        coll["all-reduce"] = coll.get("all-reduce", 0) + n_ar
    if sp > 1:
        # ring attention: K and V shards rotate (sp-1) hops forward, and
        # the backward re-rotates K/V and rotates dK/dV (~3x forward)
        kv_dev = prof.attn_kv_bytes / max(dp * mp * sp, 1)
        b = 3.0 * (sp - 1) * kv_dev
        wire += b
        n_cp = 6 * prof.attn_ops
        t_comm += b / hw.ici_bw \
            + hw.launch_us * 1e-6 * n_cp * (sp - 1)
        coll["collective-permute"] = n_cp
    cand.t_comm_s = t_comm
    cand.wire_bytes = wire
    cand.collectives = coll

    # -- memory ------------------------------------------------------------
    # persistent state (params/slots/param-grads) is genuinely live and
    # shards only over mp; transients (activations + activation grads)
    # shard over dp·sp and only LIVE_FRACTION of their static sum is
    # ever resident at once (XLA frees/reuses buffers the static walk
    # cannot see — calibration in docs/PLANNER.md §memory)
    h = prof.hbm
    act_grad = max(h["grad_bytes"] - prof.param_grad_bytes, 0.0)
    cand.peak_hbm_bytes = (
        (h["param_bytes"] + h["optimizer_slot_bytes"]
         + prof.param_grad_bytes) * shard_param
        + live_fraction * (h["activation_bytes"] + act_grad)
        / max(dp * sp, 1)
        + h["feed_bytes"] / max(dp * sp, 1))
    if cand.peak_hbm_bytes > hw.hbm_bytes:
        cand.feasible = False
        cand.reason = (f"predicted peak HBM "
                       f"{cand.peak_hbm_bytes / 1e9:.2f} GB exceeds the "
                       f"{hw.hbm_bytes / 1e9:.2f} GB budget")

    cand.t_step_s = cand.t_compute_s + cand.t_comm_s \
        + hw.dispatch_us * 1e-6
    cand.mfu = prof.report.total_flops / (n * hw.peak_flops
                                          * cand.t_step_s)


def plan_meshes(program: ir.Program,
                feed_shapes: Dict[str, Sequence[int]],
                n_devices: int,
                hw: Optional[HardwareSpec] = None,
                default_dim: Optional[int] = None,
                live_fraction: float = LIVE_FRACTION) -> PlanReport:
    """Search the dp×mp×sp factorizations of `n_devices` for `program`
    fed with `feed_shapes`; returns the ranked `PlanReport`. OOM and
    structurally-impossible candidates are kept, rejected, with their
    reason — `PlanReport.best` is the top FEASIBLE candidate."""
    hw = hw or detect_hardware()
    prof = _ProgramProfile(program, feed_shapes, default_dim)
    cands = []
    for dp, mp, sp in enumerate_meshes(n_devices):
        c = MeshPlan(dp, mp, sp)
        _evaluate(c, prof, hw, live_fraction)
        cands.append(c)
    return PlanReport(cands, n_devices, hw, prof.report)


# ---------------------------------------------------------------------------
# bucket-ladder solver (serve.BucketLadder.from_trace rides this)
# ---------------------------------------------------------------------------

def optimal_rungs(extents: Sequence[int], max_rungs: int,
                  weights: Optional[Sequence[float]] = None
                  ) -> Tuple[int, ...]:
    """Choose ≤ `max_rungs` rung values covering every observed extent,
    minimizing total padding Σ w_i·(rung(x_i) − x_i). Rungs only ever
    need to sit AT observed extents (lowering a rung to the next
    observed value below it never increases padding), so this is an
    exact O(m²·K) partition DP over the m unique extents."""
    if max_rungs < 1:
        raise ValueError(f"max_rungs must be >= 1, got {max_rungs}")
    xs = [int(x) for x in extents]
    if not xs:
        return ()
    if any(x <= 0 for x in xs):
        raise ValueError("extents must be positive")
    ws = [float(w) for w in weights] if weights is not None \
        else [1.0] * len(xs)
    if len(ws) != len(xs):
        raise ValueError("weights must match extents")
    agg: Dict[int, float] = {}
    for x, w in zip(xs, ws):
        agg[x] = agg.get(x, 0.0) + w
    uniq = sorted(agg)
    m = len(uniq)
    k = min(int(max_rungs), m)
    if k == m:
        return tuple(uniq)
    w_arr = np.array([agg[u] for u in uniq])
    u_arr = np.array(uniq, dtype=float)
    # cost[i][j]: extents (i..j] padded up to uniq[j] (i exclusive)
    cum_w = np.concatenate([[0.0], np.cumsum(w_arr)])
    cum_wx = np.concatenate([[0.0], np.cumsum(w_arr * u_arr)])

    def seg_cost(i, j):  # pad uniq[i+1..j] to uniq[j]
        return (u_arr[j] * (cum_w[j + 1] - cum_w[i + 1])
                - (cum_wx[j + 1] - cum_wx[i + 1]))

    INF = float("inf")
    best = [[INF] * m for _ in range(k + 1)]
    back = [[-1] * m for _ in range(k + 1)]
    for j in range(m):
        best[1][j] = seg_cost(-1, j)
    for r in range(2, k + 1):
        for j in range(r - 1, m):
            for i in range(r - 2, j):
                c = best[r - 1][i] + seg_cost(i, j)
                if c < best[r][j]:
                    best[r][j] = c
                    back[r][j] = i
    # the top rung must be the max extent; fewer rungs never beat k here
    # (adding a rung can only reduce padding), so read off row k
    rungs = []
    j = m - 1
    r = k
    while j >= 0 and r >= 1:
        rungs.append(uniq[j])
        j = back[r][j]
        r -= 1
    return tuple(sorted(rungs))


# ---------------------------------------------------------------------------
# XLA flag-family priors (tools/xla_flag_sweep.py --ranked rides this)
# ---------------------------------------------------------------------------

def cost_profile(report: CostReport) -> dict:
    """FLOPs-share fingerprint of a program: which op families dominate.
    This is what the flag priors (and any future placement heuristic)
    key on."""
    total = report.total_flops or 1.0
    by = report.by_type()
    matmul = sum(a["flops"] for t, a in by.items() if t in _MATMUL_FAMILY)
    conv = sum(a["flops"] for t, a in by.items() if t in _CONV_FAMILY)
    rb = sum(a["flops"] for t, a in by.items()
             if t in _REDUCE_BCAST_FAMILY
             or (t.endswith("_grad")
                 and t[:-len("_grad")] in _REDUCE_BCAST_FAMILY))
    return {
        # conv is a SUBSET of the matmul (MXU) family, so subtracting
        # matmul+rb below already excludes conv from elementwise
        "matmul_share": matmul / total,
        "conv_share": conv / total,
        "reduce_bcast_share": rb / total,
        "elementwise_share": max(0.0, 1.0 - (matmul + rb) / total),
        "arithmetic_intensity": report.total_flops
        / max(report.total_bytes, 1.0),
    }


def flag_family_priors(report: CostReport) -> Dict[str, float]:
    """Score each XLA flag FAMILY's prior for this program, from its
    cost profile. Calibrated against the recorded phase-1/phase-r
    sweeps (docs/PERF.md): the scoped-VMEM fusion budget bought +9% on
    the matmul-dominant transformer and −7% on the conv/HBM-bound
    ResNet; conv/DMA knobs are the only family worth probing first on a
    conv program. Higher = probe earlier."""
    p = cost_profile(report)
    return {
        # fusion-grouping budget: repairs matmul-chain grouping, hurts
        # already-roofline conv fusions
        "vmem_budget": p["matmul_share"] - 2.0 * p["conv_share"],
        # alternate fusion profitability models: same direction as the
        # budget, weaker recorded effect (x0.93)
        "fusion_cost": 0.6 * p["matmul_share"] - p["conv_share"],
        # producer/consumer dot-fusion shaping knobs (x0.94-0.97)
        "dot_fusion": 0.5 * p["matmul_share"],
        # reduce+broadcast grouping: softmax/layer_norm shapes
        "reduce_bcast": 2.0 * p["reduce_bcast_share"],
        # scheduler priority tweaks: weak, program-agnostic
        "scheduler": 0.2,
        # load/store vectorizer windows: elementwise-heavy programs
        "vectorizer": 0.4 * p["elementwise_share"],
        "licm": 0.1,
        # conv input/output fusion + DMA shaping: conv programs only
        "conv_dma": 2.5 * p["conv_share"],
    }
