"""Structural whole-program verifier over the Program IR.

Capability parity with the reference's compile-time checks: OpDesc
validation + InferShape before execution (reference:
framework/shape_inference.h:30, operator.cc's RuntimeInferShapeContext,
block_desc.cc consistency checks) and the standalone analysis passes
(reference: inference/analysis/analyzer.cc). TPU-native redesign: there
is no per-op C++ kernel to refuse a bad desc at dispatch time — a
malformed Program otherwise only fails deep inside XLA lowering with a
tracer error and no op provenance. This verifier runs the same class of
checks purely over the IR, before any lowering:

  - unknown op types vs the registry (grad ops resolve their forward def)
  - input-slot arity vs OpDef.input_slots / optional_slots
  - def-before-use per block, honoring parent-block lookup and the
    executor's availability rules (feeds, persistables, @SEQLEN companions)
  - write-after-write: a value overwritten before anyone read it
  - sub-block attr validity for control-flow ops
  - feed / fetch target existence
  - every optimizer op's Grad input actually written upstream (a trainable
    Parameter reaching its update op without a gradient is the classic
    silently-frozen-layer bug)
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from ..core import ir, registry
from ..core.registry import EMPTY_VAR, FWD_OP_ATTR, GRAD_OP_SUFFIX
from .diagnostics import Diagnostic, Severity, diag_for_op

# Op types the executor handles outside the registry (host-side services
# and the feed/fetch protocol ops the reference also special-cased).
PSEUDO_OPS = frozenset({"feed", "fetch", "listen_and_serv"})

# Input slots read optionally at lowering time via env.get (grad ops pull
# out-grads lazily; a missing one becomes a zero cotangent).
_OPTIONAL_READ_SLOTS = frozenset({"OutGrad"})


def verify_program(program: ir.Program,
                   feed_targets: Optional[Sequence[str]] = None,
                   fetch_targets: Optional[Sequence[str]] = None,
                   ) -> List[Diagnostic]:
    """Run all structural checks; returns diagnostics (never raises)."""
    diags: List[Diagnostic] = []
    gb = program.global_block()

    # feed/fetch targets must resolve somewhere in the program: a declared
    # variable, or (fetch) a name some global-block op actually produces /
    # (feed) a name something actually reads — the executor's env is
    # name-based, so an undeclared-but-produced name fetches fine
    produced = {n for op in gb.ops for n in op.output_arg_names}
    consumed = {n for op in gb.ops for n in op.input_arg_names}
    for name in feed_targets or ():
        if gb._find_var_recursive(name) is None and name not in consumed:
            diags.append(Diagnostic(
                "bad-feed-target", Severity.ERROR,
                f"feed target {name!r} is not a variable of the program "
                f"and nothing reads it", var=name))
    for name in fetch_targets or ():
        if gb._find_var_recursive(name) is None and name not in produced:
            diags.append(Diagnostic(
                "bad-fetch-target", Severity.ERROR,
                f"fetch target {name!r} is neither a variable of the "
                f"program nor produced by any op (fetching it would fail "
                f"only after the whole step compiled)", var=name))

    available = _initial_available(program, feed_targets)
    _verify_block(program, gb, available, diags, visited=set())
    _verify_optimizer_grads(program, diags)
    return diags


def _initial_available(program: ir.Program,
                       feed_targets: Optional[Sequence[str]]) -> Set[str]:
    """Names readable before any op runs: persistables (the startup
    program's contract), fed data vars, and their @SEQLEN companions."""
    avail: Set[str] = {EMPTY_VAR}
    feed_set = set(feed_targets) if feed_targets is not None else None
    for blk in program.blocks:
        for v in blk.vars.values():
            fed = v.is_data and (feed_set is None or v.name in feed_set)
            if v.persistable or fed:
                avail.add(v.name)
                if fed:
                    for lvl in range(v.lod_level):
                        avail.add(ir.seqlen_var_name(v.name, lvl))
    return avail


def _verify_block(program: ir.Program, block: ir.Block, available: Set[str],
                  diags: List[Diagnostic], visited: Set[int]):
    """Walk one block in execution order. `available` is mutated: names
    this block produces stay visible to the caller's later ops only when
    the caller passes the same set (control-flow sub-blocks execute inside
    their parent's env, so that is exactly right — see
    executor._CompiledProgram's produced-set walk)."""
    visited.add(block.idx)
    # write-tracking for WAW: name -> (op_idx, op) of last write; cleared on read
    unread_writes: Dict[str, tuple] = {}

    for op_idx, op in enumerate(block.ops):
        opdef = _check_op_type(program, block, op, op_idx, diags)
        _check_slots(block, op, op_idx, opdef, diags)
        _check_sub_blocks(program, block, op, op_idx, diags)

        # -- reads ---------------------------------------------------------
        is_grad = op.type.endswith(GRAD_OP_SUFFIX) and FWD_OP_ATTR in op.attrs
        for slot, names in op.inputs.items():
            optional_read = is_grad and slot in _OPTIONAL_READ_SLOTS
            for n in names:
                if n == EMPTY_VAR:
                    continue
                unread_writes.pop(n, None)
                if n in available:
                    continue
                if optional_read:
                    continue  # env.get at lowering time; missing -> zeros
                if _declared_in_chain(program, block, n):
                    diags.append(diag_for_op(
                        "read-before-write", Severity.ERROR,
                        f"input {n!r} (slot {slot!r}) is declared but "
                        f"nothing wrote it before this op — it is neither "
                        f"persistable, fed, nor produced upstream",
                        block, op_idx, op, var=n))
                else:
                    diags.append(diag_for_op(
                        "undefined-input", Severity.ERROR,
                        f"input {n!r} (slot {slot!r}) is not a variable of "
                        f"this block or any ancestor", block, op_idx, op,
                        var=n))
                available.add(n)  # report each undefined name once
        # control-flow sub-blocks read enclosing-scope names at run time
        for si in ir.sub_block_indices(op):
            if si < len(program.blocks):
                for n in ir.external_reads(program, si):
                    unread_writes.pop(n, None)
                    if n not in available \
                            and not _declared_in_chain(program, block, n):
                        diags.append(diag_for_op(
                            "undefined-input", Severity.ERROR,
                            f"sub-block {si} reads {n!r} which is not "
                            f"available in the enclosing scope",
                            block, op_idx, op, var=n))
                        available.add(n)

        # -- writes --------------------------------------------------------
        seen_here: Set[str] = set()
        for slot, names in op.outputs.items():
            for n in names:
                if n == EMPTY_VAR:
                    continue
                if n in seen_here:
                    diags.append(diag_for_op(
                        "write-after-write", Severity.ERROR,
                        f"op writes {n!r} through two output slots — the "
                        f"first value is lost before anyone reads it",
                        block, op_idx, op, var=n))
                seen_here.add(n)
                prev = unread_writes.get(n)
                if prev is not None:
                    prev_idx, prev_op = prev
                    diags.append(diag_for_op(
                        "write-after-write", Severity.ERROR,
                        f"overwrites {n!r} which op {prev_idx} "
                        f"({prev_op.type}) wrote and nothing read since — "
                        f"the earlier write is dead", block, op_idx, op,
                        var=n))
                unread_writes[n] = (op_idx, op)
                available.add(n)
                # the lowerer materializes @SEQLEN companions implicitly
                available.add(n + ir.SEQLEN_SUFFIX)
                available.add(n + ir.SEQLEN_SUFFIX + ".1")

        # sub-blocks execute within this op: verify them with the current
        # availability (their writes surface through the op's outputs /
        # carry plumbing, so the sub-set is discarded afterwards). The
        # sub-block's OWN declared vars count as available — control-flow
        # rules materialize inner names (step inputs, memories, carries)
        # from attrs before the block's first op runs.
        for si in ir.sub_block_indices(op):
            if si < len(program.blocks) and si not in visited:
                sub = program.blocks[si]
                _verify_block(program, sub, set(available) | set(sub.vars),
                              diags, visited)


def _check_op_type(program, block, op, op_idx, diags):
    """Unknown-op check; returns the OpDef driving slot arity (for grad
    ops, the FORWARD def — the grad op itself is generic) or None."""
    if op.type in PSEUDO_OPS:
        return None
    if op.type.endswith(GRAD_OP_SUFFIX) and FWD_OP_ATTR in op.attrs:
        fwd_type = op.attrs[FWD_OP_ATTR].get("type")
        if not registry.is_registered(fwd_type):
            diags.append(diag_for_op(
                "unknown-op", Severity.ERROR,
                f"grad op's forward type {fwd_type!r} is not registered",
                block, op_idx, op))
        return None  # generic slots (FwdIn/OutGrad/InGrad), no arity contract
    if not registry.is_registered(op.type):
        close = registry.close_op_names(op.type)
        hint = f" — did you mean {close}?" if close else ""
        diags.append(diag_for_op(
            "unknown-op", Severity.ERROR,
            f"op type {op.type!r} is not registered{hint}", block, op_idx,
            op))
        return None
    return registry.get_op_def(op.type)


def _check_slots(block, op, op_idx, opdef, diags):
    """Input-slot arity vs the lowering rule's signature. An unknown slot
    is a WARNING (call_rule silently drops it — almost always a typo'd
    slot name feeding zeros downstream); a missing required slot is the
    ERROR call_rule would raise mid-trace."""
    if opdef is None:
        return
    slots = set(opdef.input_slots)
    for slot in opdef.input_slots:
        if slot in opdef.optional_slots:
            continue
        names = [n for n in op.inputs.get(slot, ())]
        if not names:
            diags.append(diag_for_op(
                "missing-slot", Severity.ERROR,
                f"required input slot {slot!r} of {op.type!r} is missing "
                f"or empty (rule signature: {opdef.input_slots})",
                block, op_idx, op))
    for slot in op.inputs:
        if slot not in slots:
            diags.append(diag_for_op(
                "unknown-slot", Severity.WARNING,
                f"input slot {slot!r} is not consumed by {op.type!r} "
                f"(known slots: {opdef.input_slots}) — the value is "
                f"silently ignored at lowering", block, op_idx, op))


def _check_sub_blocks(program, block, op, op_idx, diags):
    for key in ("sub_block", "else_block"):
        idx = op.attrs.get(key)
        if idx is None or (isinstance(idx, int) and idx < 0):
            continue
        if not isinstance(idx, int) or idx >= len(program.blocks):
            diags.append(diag_for_op(
                "bad-sub-block", Severity.ERROR,
                f"attr {key}={idx!r} is not a valid block index "
                f"(program has {len(program.blocks)} blocks)",
                block, op_idx, op))
        elif idx == 0:
            diags.append(diag_for_op(
                "bad-sub-block", Severity.ERROR,
                f"attr {key}=0 references the global block as its own "
                f"sub-block", block, op_idx, op))


def _verify_optimizer_grads(program: ir.Program, diags: List[Diagnostic]):
    """Every optimizer op's Grad input must be produced upstream, and every
    trainable Parameter an optimizer touches gets exactly one live @GRAD
    write before its update op (duplicates surface as write-after-write)."""
    blk = program.global_block()
    written_before: Set[str] = set()
    for op_idx, op in enumerate(blk.ops):
        if registry.is_registered(op.type):
            opdef = registry.get_op_def(op.type)
            if "Param" in opdef.input_slots and "Grad" in opdef.input_slots:
                for pname, gname in zip(op.input("Param"), op.input("Grad")):
                    if gname not in written_before:
                        diags.append(diag_for_op(
                            "missing-grad", Severity.ERROR,
                            f"optimizer {op.type!r} updates parameter "
                            f"{pname!r} but its gradient {gname!r} is never "
                            f"written before this op — the parameter would "
                            f"train on garbage or fail to lower",
                            blk, op_idx, op, var=gname))
        written_before.update(
            n for n in op.output_arg_names if n != EMPTY_VAR)


def _declared_in_chain(program, block, name) -> bool:
    return block._find_var_recursive(name) is not None
