"""Static analysis over the Program IR: structural verification,
whole-program shape/dtype inference, and TPU-fit lints.

The reference ran compile-time InferShape over op descs before execution
(framework/shape_inference.h:30) and shipped a standalone analysis pass
manager (inference/analysis/analyzer.cc). This package is the TPU-native
analog over the JSON-serializable Program IR:

    from paddle_tpu import analysis
    diags = analysis.analyze_program(prog, fetch_targets=["loss"])
    print(analysis.format_diagnostics(diags))

Surfaces wired elsewhere: the read-only "verify" pass and the mutating
"infer_shapes" pass (ir_pass.py), `Executor.prepare(validate=...)` /
the `validate` flag (core/executor.py, flags.py), transpiler split
verification (transpiler/distribute_transpiler.py), and the
`tools/paddle_lint.py` CLI.

A second, source-level surface lives in `concurrency`: an AST-based
lock-discipline / deadlock-cycle / hold-time analyzer over the repo's
own threaded planes, exposed through `tools/race_lint.py` (see
docs/ANALYSIS.md, "Concurrency lint").
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..core import ir
from .concurrency import (ConcurrencyDiagnostic, analyze_package,  # noqa: F401
                          analyze_paths, analyze_source, baseline_key)
from .cost_model import (CostReport, OpCost, estimate_cost,  # noqa: F401
                         estimate_peak_hbm, shape_env)
from .planner import (CPU_REHEARSAL, TPU_CHIP, HardwareSpec,  # noqa: F401
                      MeshPlan, PlanReport, cost_profile,
                      detect_hardware, enumerate_meshes,
                      estimate_step_time, flag_family_priors,
                      optimal_rungs, plan_meshes)
from .diagnostics import (Diagnostic, ProgramVerificationError,  # noqa: F401
                          Severity, format_diagnostics, has_errors,
                          lint_dead_fetch_targets, lint_program,
                          sort_diagnostics)
from .shape_infer import check_program_shapes, infer_program_shapes  # noqa: F401
from .verifier import verify_program  # noqa: F401


def analyze_program(program: ir.Program,
                    feed_targets: Optional[Sequence[str]] = None,
                    fetch_targets: Optional[Sequence[str]] = None,
                    shapes: bool = True,
                    lint: bool = True) -> List[Diagnostic]:
    """Full sweep: structural verification + shape/dtype cross-check +
    TPU lints, ranked most-severe-first."""
    diags = verify_program(program, feed_targets=feed_targets,
                           fetch_targets=fetch_targets)
    if shapes and not has_errors(diags):
        # structural errors make shape propagation garbage-in; the
        # reference ordered InferShape after desc validation the same way
        diags += check_program_shapes(program)
    if lint:
        diags += lint_program(program, fetch_targets=fetch_targets)
    return sort_diagnostics(diags)
