"""Program visualization and pretty-printing.

Capability parity with the reference's debugger (reference:
python/paddle/fluid/debugger.py — pprint_program_codes :102,
draw_block_graphviz :219, which renders a BlockDesc to graphviz via the
fluid.graphviz helper). Same two entry points over the dataclass IR:

- ``pprint_program_codes(program)`` — pseudo-code listing, one line per op
  (``out1, out2 = op_type(in1, in2, attr=..)``), forward/backward split.
- ``draw_block_graphviz(block, highlights, path)`` — DOT text with op nodes
  (boxes) and var nodes (ellipses), edges for dataflow; renders with the
  ``dot`` binary when available, otherwise leaves the .dot file.
"""

from __future__ import annotations

import re
import shutil
import subprocess
from typing import Optional, Sequence

from .core import ir

__all__ = ["pprint_program_codes", "pprint_block_codes",
           "draw_block_graphviz"]


def _repr_attr(v):
    if isinstance(v, float):
        return f"{v:g}"
    if isinstance(v, str):
        return repr(v)
    if isinstance(v, (list, tuple)) and len(v) > 6:
        return f"[{len(v)} items]"
    return repr(v)


def _repr_op(op: ir.Operator) -> str:
    outs = ", ".join(op.output_arg_names) or "_"
    ins = ", ".join(op.input_arg_names)
    attrs = ", ".join(f"{k}={_repr_attr(v)}" for k, v in sorted(op.attrs.items())
                      if not k.startswith("__"))
    arg = ins if not attrs else (f"{ins}, {attrs}" if ins else attrs)
    return f"{outs} = {op.type}({arg})"


def pprint_block_codes(block: ir.Block, show_backward: bool = False) -> str:
    """One pseudo-code line per op (reference pprint_block_codes :111)."""
    lines = [f"# block {block.idx}"]
    for op in block.ops:
        is_bwd = op.type.endswith("_grad") or "@GRAD" in " ".join(
            op.output_arg_names)
        if is_bwd and not show_backward:
            continue
        lines.append("  " + _repr_op(op))
    return "\n".join(lines) + "\n"


def pprint_program_codes(program: ir.Program, show_backward: bool = False) -> str:
    return "".join(pprint_block_codes(b, show_backward)
                   for b in program.blocks)


def _dot_id(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_]", "_", name)


def draw_block_graphviz(block: ir.Block,
                        highlights: Optional[Sequence[str]] = None,
                        path: str = "./temp.dot") -> str:
    """Write a DOT dataflow graph of `block` (reference :219). Ops are
    boxes, variables ellipses; `highlights` are regex patterns whose
    matching var nodes turn red. If the `dot` binary exists, also renders
    `<path>.pdf`. Returns the DOT text."""
    pats = [re.compile(p) for p in (highlights or [])]

    def hl(name):
        return any(p.search(name) for p in pats)

    lines = ["digraph G {", "  rankdir=TB;"]
    vars_seen = set()

    def var_node(name):
        if name in vars_seen:
            return
        vars_seen.add(name)
        v = block._find_var_recursive(name) if hasattr(block, "_find_var_recursive") \
            else block.vars.get(name)
        label = name
        if v is not None and getattr(v, "shape", None) is not None:
            label += "\\n" + "x".join(str(d) for d in v.shape)
        color = "red" if hl(name) else ("lightblue" if isinstance(
            v, ir.Parameter) else "white")
        lines.append(f'  v_{_dot_id(name)} [label="{label}" shape=ellipse '
                     f'style=filled fillcolor={color}];')

    for i, op in enumerate(block.ops):
        lines.append(f'  op_{i} [label="{op.type}" shape=box style=filled '
                     f'fillcolor=gold];')
        for n in op.input_arg_names:
            var_node(n)
            lines.append(f"  v_{_dot_id(n)} -> op_{i};")
        for n in op.output_arg_names:
            var_node(n)
            lines.append(f"  op_{i} -> v_{_dot_id(n)};")
    lines.append("}")
    dot = "\n".join(lines) + "\n"
    with open(path, "w") as f:
        f.write(dot)
    if shutil.which("dot"):
        try:
            subprocess.run(["dot", "-Tpdf", path, "-o", path + ".pdf"],
                           check=False, timeout=30)
        except Exception:
            pass
    return dot
