"""Host parameter server: sharded dense params + sparse tables, barrierless
async updates.

Capability parity with the reference pserver runtime (reference:
paddle/fluid/operators/listen_and_serv_op.cc — RunSyncLoop :106,
RunAsyncLoop :195 (per-grad update, no barriers);
operators/distributed/request_handler_impl.cc RequestSend/Get/Prefetch;
lookup_sparse_table_op.cc:39 auto-grown uniform-init sparse rows;
checkpoint_notify handling).

TPU-native redesign: the trainer's compute step stays ONE jitted XLA
program; only the parameter exchange crosses the host boundary. Each server
process owns a shard of the dense params (round-robin by name, reference
ps_dispatcher) and a row shard of each sparse table (row id % num_servers,
reference split_ids_op semantics). `push_grad` applies the update
immediately under a per-param lock — the reference's barrierless async SGD
(doc/fluid/design/dist_train/async_update.md).
"""

from __future__ import annotations

import logging
import os
import socket
import struct
import threading
import time
from typing import Dict, Optional

import numpy as np

from .. import flags as _flags
from ..wire import codec as _wire_codec
from ..ark import checkpoint as ark_ckpt
from ..ark.liveness import EvictingBarrier, LeaseTable
from ..haven import replication as _haven
from ..observe import flight as _flight
from ..observe import metrics as _metrics
from ..observe import xray as _xray
from . import rpc
from .optim import make_optimizer

logger = logging.getLogger(__name__)


class _SparseTable:
    """A row shard of a distributed lookup table.

    Memory contract: the shard is an EAGER dense [rows/n_servers, width]
    array (plus same-shape optimizer accumulators on first push) — 2-3x the
    shard bytes per server. All rows are uniform-initialized up front, which
    matches the reference's lookup_sparse_table numerics (uniform min/max,
    lookup_sparse_table_op.cc:39) without its auto-grow bookkeeping. For
    vocabularies too large for dense shards, the upgrade path is a hashed
    row-dict (the reference's SelectedRows row map) — not needed at the
    scales the in-tree workloads exercise."""

    def __init__(self, local_rows: int, width: int, dtype: str,
                 init_low: float, init_high: float, seed: int):
        rng = np.random.RandomState(seed)
        self.value = rng.uniform(init_low, init_high,
                                 (local_rows, width)).astype(dtype)

    def get(self, local_ids: np.ndarray) -> np.ndarray:
        return self.value[local_ids]


class ParameterServer:
    def __init__(self, endpoint: str, trainers: int = 1,
                 sync_timeout: float = 120.0,
                 pulse_port: Optional[int] = None):
        self.endpoint = endpoint
        self.trainers = trainers
        self.sync_timeout = sync_timeout
        # fluid-pulse opt-in: start()/stop() manage the process's health
        # endpoint and this server's lease-freshness check on it
        # (requires the observe flag — start_pulse refuses otherwise)
        self._pulse_port_req = pulse_port
        self.pulse_port: Optional[int] = None
        self._dense: Dict[str, np.ndarray] = {}
        self._sparse: Dict[str, _SparseTable] = {}
        self._optim: Dict[str, object] = {}
        self._opt_cfg: Dict[str, tuple] = {}   # name -> (opt_type, lr, attrs)
        # sync mode (reference RunSyncLoop, listen_and_serv_op.cc:106):
        # per-batch gradient accumulation + a barrier whose action applies
        # the aggregated update ONCE before any trainer proceeds
        self._pending: Dict[str, np.ndarray] = {}  # guarded_by: self._pending_lock
        self._pending_lock = threading.Lock()
        # exactly-once sync accounting: per-trainer highest APPLIED batch
        # id (keyed under that trainer's session nonce, so a restarted
        # trainer whose ids restart at 0 gets a fresh watermark instead of
        # silent drops), plus the (trainer, batch) pairs accumulated into
        # the CURRENT pending batch — retried pushes for an already-applied
        # or already-accumulated batch are acknowledged but NOT
        # re-accumulated (closes the double-advance window on partial
        # barrier failure across servers)
        # trainer -> batch id
        self._sync_applied: Dict[int, int] = {}  # guarded_by: self._pending_lock
        # trainer -> nonce
        self._sync_sessions: Dict[int, object] = {}  # guarded_by: self._pending_lock
        self._sync_pending_from: set = set()  # guarded_by: self._pending_lock
        # exactly-once ASYNC accounting (fluid-haven): tagged barrierless
        # pushes carry a per-trainer monotone seq under a session nonce —
        # the async twin of the sync watermark above, which is what makes
        # a push replayed at a PROMOTED backup safe to ack-and-drop
        # trainer -> push seq
        self._async_applied: Dict[int, int] = {}  # guarded_by: self._async_lock
        self._async_sessions: Dict[int, object] = {}  # guarded_by: self._async_lock
        self._async_lock = threading.Lock()
        # fluid-haven replication state (armed by start_replication /
        # start_standby; None = the legacy solo server, zero new cost)
        self._haven = None
        # liveness (ark): heartbeat leases + an evicting barrier — a dead
        # leaseholder is evicted once its lease expires, degrading the
        # sync world to N-1 instead of wedging until sync_timeout.
        # Trainers that never heartbeat hold no lease and keep the legacy
        # full-party/sync-timeout behavior.
        self._lease = LeaseTable()
        self._sync_barrier = EvictingBarrier(trainers,
                                             action=self._apply_pending)
        # fluid-elastic scale-UP: trainer ids the sync world knows. A
        # heartbeat from a NEVER-SEEN id is a replacement/extra trainer
        # joining a running job — the barrier grows at the next
        # generation boundary (EvictingBarrier.join), never mid-batch.
        self._known_members: set = set(range(trainers))  # guarded_by: self._members_lock
        self._members_lock = threading.Lock()
        self._locks: Dict[str, threading.Lock] = {}
        self._global_lock = threading.Lock()
        self._barrier = threading.Barrier(trainers) if trainers > 1 else None
        self._listener: Optional[socket.socket] = None
        self._threads = []
        # live accepted sockets (for hard cut)
        self._conns: set = set()   # guarded_by: self._conns_lock
        self._conns_lock = threading.Lock()
        self._stop = threading.Event()

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "ParameterServer":
        host, port = rpc.parse_endpoint(self.endpoint)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        if port == 0:  # ephemeral port support for tests
            self.endpoint = f"{host}:{self._listener.getsockname()[1]}"
        self._listener.listen(64)
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"pserver@{self.endpoint}")
        t.start()
        self._threads.append(t)
        logger.info("pserver listening on %s", self.endpoint)
        if self._pulse_port_req is not None:
            from ..observe import health as _health
            from ..observe import pulse as _pulse
            self.pulse_port = _pulse.start_pulse(self._pulse_port_req)
            _health.get_engine().register_check(
                f"pserver_leases@{self.endpoint}", self._pulse_lease_check,
                ready=True)
        return self

    def _pulse_lease_check(self):
        """fluid-pulse /healthz check: heartbeat-lease freshness. Unready
        when a leaseholder's lease RECENTLY expired without the barrier
        evicting it yet — the window where a dead trainer may still
        count toward the sync world. Bounded: eviction only runs while
        someone waits on the barrier, so a trainer that departed for
        good (job finished, crash with no sync traffic) would otherwise
        hold this server at 503 forever; past 3 lease periods it is
        reported as `departed` detail, not unhealth. Expired-and-evicted
        trainers are detail too (the world already degraded around
        them)."""
        snap = self._lease.snapshot()
        evicted = self._sync_barrier.evicted
        stale, departed = [], []
        for t, rec in snap.items():
            if rec["live"] or t in evicted:
                continue
            expired_for = -rec["expires_in_s"]
            (stale if expired_for <= 3.0 * rec["lease_s"]
             else departed).append(t)
        detail = {
            "leases": {str(t): {k: v for k, v in rec.items()
                                if k != "session"}
                       for t, rec in snap.items()},
            "evicted": sorted(evicted),
            "stale": sorted(stale),
            "departed": sorted(departed),
            "live_parties": self._sync_barrier.live_parties,
        }
        return (not stale, detail)

    def serve_forever(self):
        self.start()
        self._stop.wait()

    def stop(self):
        """Hard cut, like a killed process: the listener AND every live
        connection close immediately (in-flight requests are dropped
        unanswered, waiting clients see EOF/RST), and the endpoint's
        port frees up so a restarted server can bind it."""
        self._stop.set()
        if self._haven is not None:
            # a killed process's forwarder/monitor threads die with it
            self._haven.close()
        if self.pulse_port is not None:
            from ..observe import health as _health
            _health.get_engine().unregister_check(
                f"pserver_leases@{self.endpoint}")
            self.pulse_port = None
        if self._listener is not None:
            # shutdown BEFORE close: the accept-loop thread blocked in
            # accept() holds a kernel reference — close() alone leaves
            # the port in LISTEN until that accept returns
            for f in ("shutdown", "close"):
                try:
                    (self._listener.shutdown(socket.SHUT_RDWR)
                     if f == "shutdown" else self._listener.close())
                except OSError:
                    pass
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                # linger-0 + shutdown + close: the RST close (not a FIN
                # close that parks the port in FIN_WAIT_2 for 60s) and
                # the shutdown wakes the conn thread blocked in recv so
                # the socket actually dies now
                c.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                             struct.pack("ii", 1, 0))
            except OSError:
                pass
            for f in ("shutdown", "close"):
                try:
                    (c.shutdown(socket.SHUT_RDWR) if f == "shutdown"
                     else c.close())
                except OSError:
                    pass

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            # connection threads are daemonic and untracked (tracking them
            # would leak one Thread object per reconnect on a long-lived
            # server); the SOCKETS are tracked so stop() can hard-cut
            # them. The psconn@ name is load-bearing: ark's chaos
            # injector keys client-vs-server fault targeting on it.
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True,
                             name=f"psconn@{self.endpoint}").start()

    def _serve_conn(self, conn):
        try:
            while not self._stop.is_set():
                try:
                    msg, rx = rpc.recv_msg(conn, with_size=True)
                except (ConnectionError, EOFError, OSError):
                    return
                if self._stop.is_set():
                    # a stopped server must behave like a dead process:
                    # drop the request unanswered rather than serving one
                    # last reply per open connection (crash-recovery tests
                    # depend on stop() being a hard cut)
                    return
                # fluid-xray frame: (cmd, payload[, meta]) — the optional
                # meta dict carries the client attempt's traceparent.
                # Legacy 2-tuple frames (no meta) keep working unchanged;
                # frames LONGER than we understand (a future peer) keep
                # the fields we know rather than killing the connection,
                # and anything shorter gets a named error reply.
                try:
                    cmd, payload = msg[0], msg[1]
                    meta = msg[2] if len(msg) >= 3 else None
                except (TypeError, IndexError):
                    rpc.send_msg(conn, ("err", "MalformedFrame: expected "
                                        "(cmd, payload[, meta])"))
                    continue
                obs = _flags.get_flag("observe")
                t0 = time.perf_counter() if obs else 0.0
                wctx = _xray.from_wire(meta) if obs and meta else None
                try:
                    if wctx is not None:
                        # adopt the remote parent for the handler body so
                        # the server span (and anything the handler emits)
                        # lands in the CLIENT's trace
                        with _xray.activate(wctx), \
                                _xray.span(f"rpc_server:{cmd}", cat="rpc",
                                           cmd=cmd,
                                           endpoint=self.endpoint):
                            reply = self._dispatch(cmd, payload)
                    else:
                        reply = self._dispatch(cmd, payload)
                except Exception as e:  # surface server errors to the client
                    reply = ("err", f"{type(e).__name__}: {e}")
                    if obs:
                        _flight.note("rpc_handler_error", cmd=cmd,
                                     error=f"{type(e).__name__}: {e}"[:200])
                # handler latency measured BEFORE the reply send: sendall
                # blocks on a slow-reading client and that network stall
                # must not masquerade as handler time
                handler_s = time.perf_counter() - t0 if obs else 0.0
                tx = rpc.send_msg(conn, reply)
                if obs:
                    _metrics.counter(
                        "pserver_server_requests_total",
                        "RPCs served, by command").inc(cmd=cmd)
                    _metrics.counter(
                        "pserver_server_bytes_received_total",
                        "wire bytes received by the server").inc(rx, cmd=cmd)
                    _metrics.counter(
                        "pserver_server_bytes_sent_total",
                        "wire bytes sent in replies").inc(tx, cmd=cmd)
                    _metrics.histogram(
                        "pserver_server_handler_seconds",
                        "server-side handler latency (excludes socket "
                        "wait)").observe(handler_s, cmd=cmd)
                    if reply[0] == "err":
                        _metrics.counter(
                            "pserver_server_errors_total",
                            "handler errors surfaced to clients").inc(
                                cmd=cmd)
                if cmd == "stop":
                    return
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            conn.close()

    # -- dispatch ---------------------------------------------------------
    def _dispatch(self, cmd, p):
        handler = getattr(self, f"_h_{cmd}", None)
        if handler is None:
            raise ValueError(f"unknown pserver command {cmd!r}")
        hv = self._haven
        if hv is None:   # legacy solo server: zero haven cost
            return handler(**p)
        # fluid-haven serve gate: a standby backup redirects mutations to
        # the primary (reads pass, bounded-stale); a retired server
        # redirects everything to its successor; a quiescing primary
        # HOLDS mutators so a snapshot/handover cut is consistent.
        with hv.admit(cmd) as verdict:
            if verdict is not None:
                return verdict
            reply = handler(**p)
            # replicate the applied update to the backup — but never a
            # deduplicated replay (the backup saw the original record).
            # push_grads_sync records itself under the pending lock.
            if cmd in _haven.DISPATCH_RECORDED_CMDS and \
                    reply[0] == "ok" and \
                    not (isinstance(reply[1], str)
                         and reply[1].startswith("duplicate")):
                hv.record(cmd, p)
            return reply

    def _lock(self, name):
        with self._global_lock:
            return self._locks.setdefault(name, threading.Lock())

    # -- dense params -----------------------------------------------------
    def _h_init_param(self, name, value, opt_type, lr, attrs):
        """Idempotent: first writer wins (trainer 0 pushes startup values,
        reference BCastParamsToDevices / pserver startup program analog)."""
        with self._lock(name):
            if name not in self._dense:
                self._dense[name] = np.array(value, copy=True)
                self._optim[name] = make_optimizer(opt_type, lr, attrs)
                self._opt_cfg[name] = (opt_type, float(lr), dict(attrs or {}))
        return ("ok", None)

    def _h_get_param(self, name):
        with self._lock(name):
            if name not in self._dense:
                return ("err", f"param {name!r} not initialized")
            return ("ok", self._dense[name].copy())

    def _async_seen(self, seq, trainer_id, session) -> bool:
        """fluid-haven exactly-once for tagged BARRIERLESS pushes: the
        async twin of the sync watermark. `seq` increases monotonically
        per trainer session; a push at or below the watermark was
        already applied (possibly by the pre-failover primary, already
        replicated here) and is acknowledged without re-applying — the
        rule that lets a client replay un-acked pushes at a promoted
        backup. Untagged pushes (seq None) keep legacy apply-always.

        Check-only: the watermark COMMITS via `_async_mark` after the
        apply succeeds — a push that failed to decode or apply must not
        burn its seq, or the client's replay would be acked as a
        duplicate of an update that never landed (silent loss). The
        trade-off (a retry of a partially-applied multi-param push
        re-applies its prefix) only arises from server-side apply bugs,
        where loud double-apply beats silent drop."""
        if seq is None:
            return False
        with self._async_lock:
            if session is not None and \
                    self._async_sessions.get(trainer_id) != session:
                self._async_sessions[trainer_id] = session
                self._async_applied.pop(trainer_id, None)
            return seq <= self._async_applied.get(trainer_id, -1)

    def _async_mark(self, seq, trainer_id):
        if seq is None:
            return
        with self._async_lock:
            if seq > self._async_applied.get(trainer_id, -1):
                self._async_applied[trainer_id] = seq

    def _h_push_grad(self, name, grad, seq=None, trainer_id=0,
                     session=None):
        """Barrierless: apply immediately (RunAsyncLoop semantics).
        fluid-wire: the grad may arrive as a codec-tagged payload — it is
        DEQUANTIZED here, before the optimizer applies (the server-side
        half of the wire contract); raw arrays pass through unchanged, so
        legacy clients interoperate."""
        g = _wire_codec.maybe_decode(grad)  # decode outside the lock
        if self._async_seen(seq, trainer_id, session):
            return ("ok", "duplicate: push already applied")
        with self._lock(name):
            self._optim[name].dense(self._dense[name], g)
        self._async_mark(seq, trainer_id)
        return ("ok", None)

    def _h_get_params(self, names):
        out = {}
        for n in names:
            with self._lock(n):
                if n not in self._dense:
                    return ("err", f"param {n!r} not initialized")
                out[n] = self._dense[n].copy()
        return ("ok", out)

    def _h_push_grads(self, grads, seq=None, trainer_id=0, session=None):
        # decode EVERY tensor before applying ANY (and outside the
        # locks): a malformed frame must reject the whole push — a
        # partial apply would be re-applied by the caller's retry
        decoded = [(n, _wire_codec.maybe_decode(g))
                   for n, g in grads.items()]
        if self._async_seen(seq, trainer_id, session):
            return ("ok", "duplicate: push already applied")
        for n, dec in decoded:
            with self._lock(n):
                self._optim[n].dense(self._dense[n], dec)
        self._async_mark(seq, trainer_id)
        return ("ok", None)

    # -- wire negotiation (fluid-wire) ------------------------------------
    def _h_wire_caps(self):
        """Advertise the payload codecs this server decodes. A quantizing
        client calls this once per endpoint; a LEGACY server answers with
        an unknown-command error instead, which the client reads as
        'negotiate down to raw' — mixed versions interoperate, never
        corrupt."""
        return ("ok", {"codecs": list(_wire_codec.CODECS), "version": 1})

    # -- sparse tables ----------------------------------------------------
    def _h_init_table(self, name, local_rows, width, dtype, init_low,
                      init_high, seed, opt_type, lr, attrs):
        with self._lock(name):
            if name not in self._sparse:
                self._sparse[name] = _SparseTable(local_rows, width, dtype,
                                                  init_low, init_high, seed)
                self._optim[name] = make_optimizer(opt_type, lr, attrs)
                self._opt_cfg[name] = (opt_type, float(lr), dict(attrs or {}))
        return ("ok", None)

    def _h_prefetch(self, name, local_ids, codec=None):
        """Row fetch by LOCAL ids (client did the id%N sharding split,
        reference prefetch op + split_ids_op). `codec` (fluid-wire,
        negotiated clients only) returns the rows as a quantized tagged
        payload — embedding-row pulls are the recsys bandwidth hog."""
        with self._lock(name):
            rows = self._sparse[name].get(np.asarray(local_ids))
        if codec and codec != "raw" and rows.dtype == np.float32:
            return ("ok", _wire_codec.encode_tensor(rows, codec, name=name))
        return ("ok", rows)

    def _h_push_sparse_grad(self, name, local_ids, row_grads, seq=None,
                            trainer_id=0, session=None):
        # decode BEFORE the watermark advances (see _h_push_grad)
        rows = _wire_codec.maybe_decode(row_grads)
        if self._async_seen(seq, trainer_id, session):
            return ("ok", "duplicate: push already applied")
        with self._lock(name):
            table = self._sparse[name]
            self._optim[name].sparse(table.value, np.asarray(local_ids),
                                     rows)
        self._async_mark(seq, trainer_id)
        return ("ok", None)

    # -- sync-mode barrier (reference RunSyncLoop batch barrier) -----------
    def _h_batch_barrier(self):
        if self._barrier is not None:
            self._barrier.wait()
        return ("ok", None)

    # -- sync mode: per-batch accumulate + barrier-apply -------------------
    # (reference RunSyncLoop, listen_and_serv_op.cc:106: kRequestSend from
    # every trainer, then the optimize blocks run once on the aggregated
    # gradients, then kRequestGet unblocks)
    def _h_push_grads_sync(self, grads, batch_id=None, trainer_id=0,
                           session=None):
        """Accumulate this trainer's gradients for the CURRENT batch; the
        update is applied at the sync_apply barrier, not here.

        `batch_id` is a per-trainer monotonically increasing tag (the
        client keeps it stable across retries of the same batch): a push
        for a batch this server already APPLIED from this trainer — the
        partial-failure retry case where another server's barrier broke
        but this one completed — is acknowledged without re-accumulating,
        as is a duplicate (trainer, batch) push within the pending batch
        (e.g. a client resend on a dropped connection). `session` is a
        per-trainer-process nonce: a RESTARTED trainer restarts its ids
        at 0 under a new session, which resets its watermark — its pushes
        must accumulate, not be dropped as stale duplicates. Untagged
        pushes keep the legacy accumulate-always behavior."""
        # fluid-wire: dequantize tagged payloads BEFORE taking the pending
        # lock — the decode is O(gradient bytes) and must not serialize
        # concurrent trainers' pushes (the rare deduplicated replay just
        # wastes one decode). The pending sum stays full-precision f32.
        decoded = {n: _wire_codec.maybe_decode(g) for n, g in grads.items()}
        with self._pending_lock:
            if batch_id is not None:
                if session is not None and \
                        self._sync_sessions.get(trainer_id) != session:
                    self._sync_sessions[trainer_id] = session
                    self._sync_applied.pop(trainer_id, None)
                    # purge the dead session's pending markers so the new
                    # session's first push is ACCUMULATED, not dropped as
                    # a duplicate. (Its gradient bytes, if any, are
                    # already summed into _pending and cannot be
                    # subtracted — same as legacy; the barrier timeout
                    # normally clears that batch before a restart rejoins)
                    self._sync_pending_from = {
                        (t, b) for t, b in self._sync_pending_from
                        if t != trainer_id}
                if batch_id <= self._sync_applied.get(trainer_id, -1):
                    return ("ok", "duplicate: batch already applied")
                key = (trainer_id, batch_id)
                if key in self._sync_pending_from:
                    return ("ok", "duplicate: push already accumulated")
                self._sync_pending_from.add(key)
            for n, g in decoded.items():
                self._pending[n] = (g if n not in self._pending
                                    else self._pending[n] + g)
            if self._haven is not None:
                # fluid-haven: record INSIDE the pending lock (not at
                # dispatch-return) so the log order equals the
                # accumulation order — with 3+ concurrent trainers a
                # post-lock record could log in a different order than
                # the pending sum folded, and float non-associativity
                # would break the backup's bit-identity. The record
                # carries the ORIGINAL (possibly codec-tagged) grads.
                self._haven.record(
                    "push_grads_sync",
                    {"grads": grads, "batch_id": batch_id,
                     "trainer_id": trainer_id, "session": session})
        return ("ok", None)

    def _apply_pending(self, n_contrib=None, replicated=False):
        """Barrier action: runs exactly once per batch, in one of the
        waiting connection threads, before any trainer is released. The
        aggregated gradient is AVERAGED over trainers (each trainer's
        grad is the mean over its local shard, so the applied update
        equals single-process training on the combined batch — the
        ParallelExecutor CoeffNumDevice convention).

        fluid-haven: a replicating primary records the apply as one
        synthesized record carrying the contributor count, INSIDE the
        pending lock so it orders exactly between this batch's pushes
        and the next batch's; the backup replays it with the same
        divisor (`n_contrib` set, `replicated=True`) instead of
        re-deriving one from its own barrier (it has none). The
        sync_apply DISPATCH is not a counted mutator (a barrier wait
        must never hold a quiesce hostage) — the actual state mutation
        enters the gate here instead."""
        if self._haven is not None and not replicated:
            with self._haven.mutator():
                if self._haven.role != "primary":
                    # the shard was handed over while this apply waited
                    # out the quiesce: applying here would ack a batch
                    # the successor still holds pending — break the
                    # barrier instead; the trainers' retry re-pushes
                    # (deduped by the snapshotted watermarks) and the
                    # SUCCESSOR's barrier applies the batch exactly once
                    raise RuntimeError(
                        "sync barrier broken: shard handed over "
                        "mid-batch; retry the step at the new primary")
                return self._apply_pending_impl(n_contrib, replicated)
        return self._apply_pending_impl(n_contrib, replicated)

    def _apply_pending_impl(self, n_contrib=None, replicated=False):
        with self._pending_lock:
            pending, self._pending = self._pending, {}
            # distinct trainers whose gradients are actually summed into
            # this batch — the correct mean divisor. A trainer that
            # PUSHED and then died before the barrier still contributed;
            # dividing by the (smaller) live count would over-weight the
            # update by N/(N-1). Untagged legacy pushes leave no keys —
            # fall back to the live party count there.
            contributors = {t for t, _b in self._sync_pending_from}
            for t, b in self._sync_pending_from:
                if b > self._sync_applied.get(t, -1):
                    self._sync_applied[t] = b
            self._sync_pending_from.clear()
            if n_contrib is None:
                n_contrib = len(contributors) or \
                    self._sync_barrier.live_parties
            if not replicated and self._haven is not None and pending:
                self._haven.record_sync_apply(n_contrib)
        for n, g in pending.items():
            with self._lock(n):
                self._optim[n].dense(self._dense[n],
                                     g / max(n_contrib, 1))

    # -- liveness (ark): heartbeat leases + eviction -----------------------
    def _h_heartbeat(self, trainer_id, session=None, lease_s=3.0):
        """Renew `trainer_id`'s liveness lease. A previously-evicted
        trainer that heartbeats again (a restart rejoining) is
        readmitted — the barrier's party count grows back and its fresh
        session nonce resets its sync watermark on first push."""
        self._lease.beat(trainer_id, session=session, lease_s=lease_s)
        key = trainer_id if isinstance(trainer_id, str) else int(trainer_id)
        if self._sync_barrier.readmit(key):
            logger.info("pserver %s: trainer %s readmitted after "
                        "heartbeat (lease %.1fs)", self.endpoint,
                        trainer_id, lease_s)
            # lease transitions go to the black box unconditionally —
            # they are rare and exactly what a postmortem wants
            _flight.note("lease_readmit", trainer_id=key,
                         endpoint=self.endpoint)
            if _flags.get_flag("observe"):
                _metrics.counter(
                    "pserver_trainers_readmitted_total",
                    "evicted trainers readmitted after a fresh "
                    "heartbeat").inc()
        else:
            with self._members_lock:
                is_new = key not in self._known_members
                if is_new:
                    self._known_members.add(key)
            if is_new and self._sync_barrier.join(key):
                # fluid-elastic: a NEW leaseholder grows the sync world
                # at the next barrier epoch (never mid-batch); its first
                # pull reads the current params, its fresh session nonce
                # starts a fresh sync watermark
                logger.info(
                    "pserver %s: NEW trainer %s admitted to the sync "
                    "world (grows to %d at the next barrier epoch, "
                    "lease %.1fs)", self.endpoint, key,
                    self._sync_barrier.live_parties, lease_s)
                _flight.note("lease_admit", trainer_id=key,
                             endpoint=self.endpoint)
                if _flags.get_flag("observe"):
                    _metrics.counter(
                        "pserver_trainers_admitted_total",
                        "new trainers admitted to a running sync world "
                        "on first heartbeat").inc()
        return ("ok", {"live_trainers": self._sync_barrier.live_parties,
                       "leases": self._lease.snapshot()})

    def _evict_expired(self):
        """Barrier-wait callback: evict leaseholders whose lease expired
        so the sync world degrades to the live N-1 instead of wedging
        until sync_timeout. Only ever called while some trainer is
        waiting — an idle server expires no one."""
        for tid in self._lease.expired():
            if self._sync_barrier.evict(tid):
                logger.warning(
                    "pserver %s: trainer %s lease expired — evicted from "
                    "the sync barrier (world degrades to %d live "
                    "trainers)", self.endpoint, tid,
                    self._sync_barrier.live_parties)
                _flight.note("lease_evict", trainer_id=tid,
                             endpoint=self.endpoint,
                             live_parties=self._sync_barrier.live_parties)
                if _flags.get_flag("observe"):
                    _metrics.counter(
                        "pserver_trainers_evicted_total",
                        "trainers evicted on lease expiry").inc()
                    # an eviction span on the timeline: zero-duration mark
                    # in whatever trace the waiting arrival activated
                    with _xray.span("lease_evict", cat="ark",
                                    trainer_id=tid,
                                    endpoint=self.endpoint):
                        pass

    def _h_sync_apply(self, trainer_id=None):
        try:
            self._sync_barrier.wait(timeout=self.sync_timeout,
                                    evict_check=self._evict_expired,
                                    member=trainer_id)
        except threading.BrokenBarrierError:
            # recover rather than poison the long-lived server: the FIRST
            # recovering thread (the one that still observes the barrier
            # broken, under the lock) discards the incomplete batch's
            # accumulated gradients and resets the barrier; later
            # recoverers skip both, so gradients a fast trainer already
            # RE-pushed for the retry are never wiped. The partial-failure
            # case (one server's barrier trips, another's completes) is
            # closed by the batch-id tags on push_grads_sync: the healthy
            # shard rejects the retried batch's pushes as already-applied,
            # so its barrier fires on an EMPTY pending set and the retried
            # batch cannot double-advance it.
            with self._pending_lock:
                if self._sync_barrier.broken:
                    self._pending.clear()
                    self._sync_pending_from.clear()
                    self._sync_barrier.reset()
                    if self._haven is not None:
                        # fluid-haven: the discard must replicate — the
                        # backup's replayed pending holds the broken
                        # batch's pushes, and without the reset the
                        # retried batch would dedup against them and
                        # the copies would silently diverge
                        self._haven.record(_haven.SYNC_RESET_RECORD, {})
            return ("err", "sync barrier broken (a trainer died or timed "
                           "out mid-batch); batch discarded, barrier "
                           "reset — retry the step")
        return ("ok", None)

    # -- checkpoint (reference checkpoint_notify -> save block) ------------
    def _shard_path(self, dirname, endpoint=None):
        ep = endpoint or self.endpoint
        return os.path.join(dirname, f"pserver_{ep.replace(':', '_')}.npz")

    def _h_save(self, dirname):
        """Snapshot values AND optimizer state (accumulators + config) so
        a crashed server can be restarted from its shard and training
        resumes with identical update dynamics (reference checkpoint_notify
        -> save block on the pserver, request_handler_impl.cc).

        Joins the ark atomic/manifest protocol: the npz lands via tmp +
        os.replace (a crash mid-save never tears an existing shard) and a
        sha256 sidecar manifest commits after it, so `recover()` and
        `ark.verify_checkpoint` can prove the shard intact. When
        `dirname` is a checkpoint stage dir (trainer-driven
        `save_checkpoint(shard_saver=...)`), the shard commits as part of
        the same all-or-nothing serial.

        fluid-haven: on a replicating server the snapshot is taken under
        a brief quiesce (in-flight mutators drain, new ones are held) so
        the shard is a consistent cut, and the sidecar manifest is
        tagged with the replication watermark (`haven_seq`) + fencing
        epoch — the checkpoint names exactly which prefix of the update
        stream it contains."""
        if self._haven is not None:
            with self._haven.quiesce():
                st = self._haven.status()
                return self._save_impl(
                    dirname,
                    haven_seq=(st["head_seq"] if st["role"] == "primary"
                               else st["applied_seq"]),
                    haven_epoch=st["epoch"], haven_role=st["role"])
        return self._save_impl(dirname)

    def _save_impl(self, dirname, **sidecar_extra):
        import json

        os.makedirs(dirname, exist_ok=True)
        # snapshot each param under its own lock so a checkpoint racing
        # concurrent pushes is internally consistent per-param (the async
        # mode has no global consistent cut — same as the reference)
        arrays, meta = {}, {}
        for kind, names in (("dense", list(self._dense)),
                            ("sparse", list(self._sparse))):
            for n in names:
                with self._lock(n):
                    val = (self._dense[n] if kind == "dense"
                           else self._sparse[n].value)
                    arrays[f"{'d' if kind == 'dense' else 's'}::{n}"] = \
                        val.copy()
                    # optimizer state through its own API (one source of
                    # truth for what constitutes state), arrays flattened
                    # into the npz
                    st = self._optim[n].state()
                    for k, a in st["acc"].items():
                        arrays[f"o::{n}::{k}"] = np.array(a, copy=True)
                opt_type, _, _ = self._opt_cfg[n]
                meta[n] = {"kind": kind, "opt_type": opt_type,
                           "lr": st["lr"], "attrs": st["attrs"]}
        path = self._shard_path(dirname)
        with ark_ckpt.atomic_file(path) as f:
            np.savez(f, __meta__=np.array(json.dumps(meta)), **arrays)
        ark_ckpt.write_sidecar_manifest(path, endpoint=self.endpoint,
                                        kind="pserver_shard",
                                        **sidecar_extra)
        return ("ok", path)

    def recover(self, dirname,
                shard_endpoint: Optional[str] = None) -> "ParameterServer":
        """Restore this server's shard from `dirname` (written by a prior
        save on the SAME endpoint). Values, sparse tables, and optimizer
        accumulators all come back, so resumed training continues the
        exact update sequence — the crash-restart leg of the reference's
        checkpoint/notify protocol (trainer.py:986 resume path).

        fluid-haven: `shard_endpoint` names the PEER whose shard file to
        load — how a promoted former-backup (or a fresh process on a new
        port) recovers the checkpoint its dead primary wrote."""
        import json

        path = self._shard_path(dirname, endpoint=shard_endpoint)
        # checksum gate BEFORE deserializing: a torn/bit-rotted shard is
        # refused loudly, never half-loaded (no sidecar = pre-ark shard,
        # loaded as before)
        ark_ckpt.verify_sidecar(path)
        with np.load(path, allow_pickle=False) as z:
            meta = json.loads(str(z["__meta__"]))
            for name, m in meta.items():
                with self._lock(name):
                    if m["kind"] == "dense":
                        self._dense[name] = z[f"d::{name}"].copy()
                    else:
                        tbl = _SparseTable.__new__(_SparseTable)
                        tbl.value = z[f"s::{name}"].copy()
                        self._sparse[name] = tbl
                    opt = make_optimizer(m["opt_type"], m["lr"], m["attrs"])
                    prefix = f"o::{name}::"
                    opt.load_state({"lr": m["lr"], "attrs": m["attrs"],
                                    "acc": {k[len(prefix):]: z[k].copy()
                                            for k in z.files
                                            if k.startswith(prefix)}})
                    self._optim[name] = opt
                    self._opt_cfg[name] = (m["opt_type"], m["lr"],
                                           m["attrs"])
        return self

    def _h_restore(self, dirname, shard_endpoint=None):
        self.recover(dirname, shard_endpoint=shard_endpoint)
        if self._haven is not None:
            # the shard state changed out-of-band: the update log can no
            # longer bring the backup current — force a full resync
            self._haven.mark_resync()
        return ("ok", sorted(self._dense) + sorted(self._sparse))

    # -- fluid-haven: replication / election / handoff ---------------------
    def _arm_quorum(self, quorum_endpoints, quorum_resource,
                    quorum_lease_s, lease_s):
        """fluid-quorum opt-in shared by both haven roles: build the
        arbiter client (attributed to THIS server for chaos partition
        rules) and attach it as the shard's election source. Both
        members of a pair must name the same resource."""
        from ..quorum import QuorumClient
        lease = float(quorum_lease_s or lease_s)
        client = QuorumClient(
            list(quorum_endpoints), actor=self.endpoint,
            # short per-node deadline: a renewal round must resolve well
            # inside lease/3 even with one arbiter blackholed
            deadline_s=max(0.25, min(1.0, lease / 4.0)))
        self._haven.arm_quorum(client, quorum_resource or "ps-shard-0",
                               lease_s=lease)

    def start_replication(self, backup_endpoint: str, lease_s: float = 2.0,
                          window: int = 512, stall_timeout_s: float = 5.0,
                          quorum_endpoints=None,
                          quorum_resource: Optional[str] = None,
                          quorum_lease_s: Optional[float] = None
                          ) -> "ParameterServer":
        """Arm this server as the PRIMARY of a replicated pair: every
        applied update is forwarded to `backup_endpoint` as a
        sequence-numbered record; the forwarder's batches double as the
        primary's lease renewal on the backup. The first batch performs
        a full snapshot sync, so the backup may start empty.

        `quorum_endpoints` (fluid-quorum, a 3/5-node arbiter group)
        upgrades the pair's failure model to partition-tolerant: this
        primary must win — and keep renewing — a majority-granted lease
        on `quorum_resource`, failing closed when it cannot."""
        from ..haven import HavenState
        if self._haven is None:
            self._haven = HavenState(self, role="primary", lease_s=lease_s,
                                     window=window,
                                     stall_timeout_s=stall_timeout_s)
        self._haven.lease_s = float(lease_s)
        if quorum_endpoints:
            self._arm_quorum(quorum_endpoints, quorum_resource,
                             quorum_lease_s, lease_s)
        self._haven.start_replication(backup_endpoint)
        return self

    def start_standby(self, lease_s: float = 2.0,
                      auto_promote: bool = True,
                      quorum_endpoints=None,
                      quorum_resource: Optional[str] = None,
                      quorum_lease_s: Optional[float] = None
                      ) -> "ParameterServer":
        """Arm this server as a standby BACKUP: it replays the primary's
        record stream, serves bounded-stale reads, redirects writes, and
        (with `auto_promote`) promotes itself when the primary's lease
        expires. A handover target passes `auto_promote=False` so a torn
        handover can never elect two primaries.

        With `quorum_endpoints` configured, self-promotion additionally
        requires a majority-granted quorum lease — `auto_promote=True`
        is then safe even on partition-risky networks (the standby of a
        merely-partitioned pair loses the election instead of
        split-braining)."""
        from ..haven import HavenState
        if self._haven is None:
            self._haven = HavenState(self, role="backup", lease_s=lease_s)
        self._haven.lease_s = float(lease_s)
        if quorum_endpoints:
            self._arm_quorum(quorum_endpoints, quorum_resource,
                             quorum_lease_s, lease_s)
        self._haven.start_standby(auto_promote=auto_promote)
        return self

    def handover(self, new_endpoint: str, timeout: float = 30.0) -> dict:
        """Planned live shard handoff to a fresh standby process (see
        HavenState.handover): drain, snapshot+tail stream, lease flip,
        retire — zero failed trainer pushes across the flip."""
        from ..haven import HavenState
        if self._haven is None:   # solo server moving hosts
            self._haven = HavenState(self, role="primary")
        return self._haven.handover(new_endpoint, timeout=timeout)

    def _h_haven_role(self):
        if self._haven is None:
            return ("ok", {"role": "solo", "epoch": -1,
                           "endpoint": self.endpoint,
                           "primary": self.endpoint})
        return ("ok", self._haven.status())

    def _ensure_standby(self, auto_promote=True):
        if self._haven is None:
            # a bare server adopted by a primary arms itself on first
            # contact (lease_s refreshed from the primary's batches)
            self.start_standby(auto_promote=auto_promote)
        return self._haven

    def _h_haven_replicate(self, records, epoch, primary, lease_s=2.0):
        return self._ensure_standby().replay(records, epoch, primary,
                                             lease_s)

    def _h_haven_sync(self, snapshot, lease_s=2.0):
        return self._ensure_standby().install_snapshot(snapshot,
                                                       lease_s=lease_s)

    def _h_haven_promote(self, epoch, backup=None, predecessor=None):
        hv = self._ensure_standby(auto_promote=False)
        hv.promote(kind="handover", epoch=epoch, backup=backup,
                   predecessor=predecessor)
        return ("ok", {"epoch": hv.epoch, "role": hv.role})

    def _h_stats(self):
        return ("ok", {"dense": sorted(self._dense),
                       "sparse": sorted(self._sparse),
                       "endpoint": self.endpoint})

    def _h_stop(self):
        self.stop()
        return ("ok", None)
