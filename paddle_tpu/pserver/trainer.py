"""AsyncPSTrainer: drives a transpiled trainer program against pservers.

Capability parity with the reference's async trainer loop (reference:
trainer program send/recv ops injected by distribute_transpiler.py:248-309;
async update design doc/fluid/design/dist_train/async_update.md; sparse
prefetch path distribute_transpiler.py:316 + split_ids/merge_ids ops).

TPU-native redesign: the jitted step cannot issue RPCs, so each reference
distributed op becomes a host phase around `exe.run`:

    recv ops      -> pull dense params into the scope before the step
    prefetch op   -> fetch the batch's unique table rows, feed them as a
                     [cap, width] sub-table UNDER THE TABLE'S NAME with ids
                     remapped to sub-table rows (feeds override scope state,
                     and the executor compiles per feed signature, so the
                     program needs no rewriting)
    send ops      -> push dense grads + scatter sub-table row grads after
                     the step (barrierless — RunAsyncLoop semantics)
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import flags as _flags
from ..core import executor as core_exec
from ..observe import health as _health
from ..observe import metrics as _metrics
from ..observe import xray as _xray
from .client import PSClient


def _note_step_health(user_outs, grads):
    """fluid-pulse (observe on): land this step's loss and gradient norm
    on the health plane's time-series via the registry emit path the
    engine watches — food for the non-finite and grad-norm-spike
    detectors. The fetched arrays are already on the host; the norm
    accumulates per-tensor vdot scalars in the NATIVE dtype (no float64
    copy of the model's gradients per step — the observe overhead
    contract is cheap host scalars, not O(model-bytes) traffic)."""
    if user_outs:
        _health.note_loss_fetch(user_outs)
    if grads:
        sq = 0.0
        for g in grads:
            a = np.asarray(g).reshape(-1)
            sq += float(np.vdot(a, a))
        _metrics.gauge("trainer_grad_norm",
                       "L2 norm of this step's pushed gradients").set(
                           float(np.sqrt(sq)))


# lazily-initialized sparse rows are uniform in this range (reference
# lookup_sparse_table_op.cc min/max attrs default -1/1; embeddings converge
# better from a tighter band)
TABLE_INIT_LOW, TABLE_INIT_HIGH = -0.05, 0.05


class AsyncPSTrainer:
    def __init__(self, transpiler, exe, program=None, scope=None):
        self.t = transpiler
        self.exe = exe
        self.scope = scope or core_exec.global_scope()
        self.program = program or transpiler.get_trainer_program()
        # fluid-wire: the transpiler config's comm_quant rides into the
        # client so pserver pushes/pulls travel quantized (negotiated per
        # endpoint; legacy servers degrade to raw).
        # fluid-haven: config.haven_replicas ({primary: [backup, ...]})
        # arms read AND write failover — pushes are seq-tagged so a
        # replay at a promoted backup dedups server-side instead of
        # double-applying.
        replicas = getattr(transpiler.config, "haven_replicas", None)
        self.client = PSClient(
            transpiler._pserver_endpoints,
            comm_quant=getattr(transpiler.config, "comm_quant", None),
            replicas=replicas,
            dedup_pushes=replicas is not None,
            trainer_id=transpiler._trainer_id,
            quorum_endpoints=getattr(transpiler.config,
                                     "quorum_endpoints", None),
            quorum_resources=getattr(transpiler.config,
                                     "quorum_resources", None))
        self.trainer_id = transpiler._trainer_id
        # tables sharing any ids feed must share one uniq/remap (a fed ids
        # var can only hold ONE remapping) — group them transitively
        self._table_groups = self._group_tables(transpiler.sparse_specs)

    @staticmethod
    def _group_tables(sparse_specs):
        groups: List[dict] = []  # {"tables": [...], "ids_names": [...]}
        for wname, spec in sparse_specs.items():
            hit = None
            for g in groups:
                if set(spec["ids_names"]) & set(g["ids_names"]):
                    hit = g
                    break
            if hit is None:
                hit = {"tables": [], "ids_names": []}
                groups.append(hit)
            hit["tables"].append(wname)
            for n in spec["ids_names"]:
                if n not in hit["ids_names"]:
                    hit["ids_names"].append(n)
        # merge transitively-overlapping groups
        merged = True
        while merged:
            merged = False
            for i in range(len(groups)):
                for j in range(i + 1, len(groups)):
                    if set(groups[i]["ids_names"]) & set(groups[j]["ids_names"]):
                        groups[i]["tables"] += groups[j]["tables"]
                        groups[i]["ids_names"] += [
                            n for n in groups[j]["ids_names"]
                            if n not in groups[i]["ids_names"]]
                        del groups[j]
                        merged = True
                        break
                if merged:
                    break
        return groups

    # -- startup ----------------------------------------------------------
    def _lr_of(self, spec) -> float:
        name = spec.get("lr_name")
        if name is None:
            raise ValueError(
                "optimizer op carries no LearningRate input; async PS mode "
                "needs one")
        v = self.scope.find_var(name)
        if v is None:
            # a missing scope var means the LR is COMPUTED in-program (a
            # decay schedule) — silently defaulting would train at the
            # wrong rate forever, so refuse loudly
            raise ValueError(
                f"learning-rate var {name!r} is not materialized in the "
                f"scope. Async PS mode applies updates server-side with a "
                f"constant LR captured at init_params(); in-program LR "
                f"schedules (learning_rate_scheduler.*) are not supported "
                f"on this path — pass a float learning_rate (reference "
                f"async pservers share the limitation for sparse tables)")
        return float(np.asarray(v).reshape(-1)[0])

    def init_params(self):
        """Every trainer offers its startup values; the server keeps the
        first writer's (reference: pserver startup program / param bcast)."""
        for pname, spec in self.t.param_specs.items():
            value = np.asarray(self.scope.find_var(pname))
            self.client.init_param(spec["endpoint"], pname, value,
                                   spec["opt_type"], self._lr_of(spec),
                                   spec["attrs"])
        for wname, spec in self.t.sparse_specs.items():
            self.client.init_table(
                wname, spec["rows"], spec["width"], spec["dtype"],
                TABLE_INIT_LOW, TABLE_INIT_HIGH, seed=1337,
                opt_type=spec["opt_type"], lr=self._lr_of(spec),
                attrs=spec["attrs"])

    def _scope_kw(self) -> Dict:
        """The jitted step must run against the trainer's scope when one
        was given explicitly; duck-typed executor adapters (e.g. a
        ParallelExecutor wrapper, which owns its scope) may not accept a
        scope kwarg, so the global-scope default passes nothing."""
        if self.scope is core_exec.global_scope():
            return {}
        return {"scope": self.scope}

    def _recv_dense(self):
        """Pull the dense params into the scope — ONE batched RPC per
        endpoint, in parallel (reference overlaps AsyncGetVar handles the
        same way)."""
        by_ep: Dict[str, List[str]] = {}
        for pname, spec in self.t.param_specs.items():
            by_ep.setdefault(spec["endpoint"], []).append(pname)
        for ep, values in self.client.get_params_parallel(by_ep).items():
            for pname, value in values.items():
                self.scope.set_var(pname, value)

    def _dense_grads_by_ep(self, grads) -> Dict[str, Dict[str, np.ndarray]]:
        by_ep: Dict[str, Dict[str, np.ndarray]] = {}
        for (pname, spec), g in zip(self.t.param_specs.items(), grads):
            by_ep.setdefault(spec["endpoint"], {})[pname] = g
        return by_ep

    # -- one async step ---------------------------------------------------
    def step(self, feed: Dict, fetch_list: Sequence) -> List[np.ndarray]:
        # fluid-xray: one span per training step so the pull/compute/push
        # RPC spans of this step (and their server-side halves) nest
        # under one parent in the merged timeline
        if _flags.get_flag("observe"):
            with _xray.span("train_step", cat="train",
                            trainer_id=self.trainer_id):
                return self._step_impl(feed, fetch_list)
        return self._step_impl(feed, fetch_list)

    def _step_impl(self, feed: Dict, fetch_list: Sequence
                   ) -> List[np.ndarray]:
        # 1. recv the freshest dense params
        self._recv_dense()

        # 2. prefetch: per table GROUP (tables sharing an ids feed share one
        # uniq/remap — the fed ids var can only hold one mapping)
        feed = dict(feed)
        pushes = []  # (wname, unique_ids[m])
        for g in self._table_groups:
            ids_vals = [np.asarray(feed[n]) for n in g["ids_names"]]
            flat = np.concatenate([v.reshape(-1) for v in ids_vals])
            uniq, inv = np.unique(flat, return_inverse=True)
            m = uniq.shape[0]
            if m == 0:  # empty tail batch: feed zero tables, nothing to push
                for wname in g["tables"]:
                    spec = self.t.sparse_specs[wname]
                    feed[wname] = np.zeros((spec["cap"], spec["width"]),
                                           dtype=spec["dtype"])
                continue
            for wname in g["tables"]:
                spec = self.t.sparse_specs[wname]
                if m > spec["cap"]:
                    raise ValueError(
                        f"batch touches {m} unique rows of {wname!r} but "
                        f"sparse_prefetch_cap={spec['cap']}; raise "
                        f"DistributeTranspilerConfig.sparse_prefetch_cap")
                sub = np.zeros((spec["cap"], spec["width"]),
                               dtype=spec["dtype"])
                sub[:m] = self.client.prefetch_rows(wname, uniq)
                feed[wname] = sub
                pushes.append((wname, uniq))
            off = 0
            for n, v in zip(g["ids_names"], ids_vals):
                feed[n] = inv[off:off + v.size].reshape(v.shape).astype(v.dtype)
                off += v.size

        # 3. the jitted step, fetching user targets + every grad
        grad_fetches = [self.t.grad_names[p] for p in self.t.param_specs]
        grad_fetches += [self.t.grad_names[w] for w, _ in pushes]
        outs = self.exe.run(self.program, feed=feed,
                            fetch_list=list(fetch_list) + grad_fetches,
                            **self._scope_kw())
        user_outs = outs[: len(fetch_list)]
        grads = outs[len(fetch_list):]

        # 4. send: barrierless pushes, batched per endpoint
        self.client.push_grads_parallel(self._dense_grads_by_ep(grads))
        for (wname, uniq), g in zip(pushes,
                                    grads[len(self.t.param_specs):]):
            self.client.push_sparse_grad(wname, uniq, g[: uniq.shape[0]])
        if _flags.get_flag("observe"):
            _note_step_health(user_outs, grads[: len(self.t.param_specs)])
        return user_outs

    def save(self, dirname):
        """checkpoint_notify analog: every pserver snapshots its shard."""
        return self.client.save(dirname)

    def close(self):
        self.client.close()


class SyncPSTrainer(AsyncPSTrainer):
    """Sync-mode parameter-server training — the process-based analog of
    the reference's RunSyncLoop (listen_and_serv_op.cc:106): every batch,
    all trainers send their gradients, a per-batch barrier fires the
    aggregated update ONCE server-side, and only then does any trainer
    proceed (its next pull reads the post-update params — the reference's
    kRequestGet-after-optimize ordering).

    Dense parameters only: distributed lookup tables are inherently
    barrierless on the host path (use async or hybrid mode — reference
    deployments run sparse CTR async for the same reason). On TPU the
    RECOMMENDED sync data-parallel path remains GSPMD collectives
    (DistributeTranspiler default); this runtime exists for reference
    execution-mode parity and for host-only deployments.
    """

    def __init__(self, transpiler, exe, program=None, scope=None,
                 heartbeat_lease_s=None):
        super().__init__(transpiler, exe, program=program, scope=scope)
        if transpiler.sparse_specs:
            raise NotImplementedError(
                "sync PS mode is dense-only: distributed lookup tables "
                "update barrierlessly (reference runs sparse CTR async); "
                "use sync_mode=False or mode='hybrid'")
        # monotone batch tag; advanced only after a SUCCESSFUL sync_apply,
        # so a retried batch re-pushes under the SAME id and servers that
        # already applied it reject the duplicate accumulation. The
        # session nonce distinguishes a RESTARTED trainer (ids restart at
        # 0 legitimately) from a duplicate push of an applied batch.
        import uuid
        self._batch_id = 0
        self._session = uuid.uuid4().hex
        # liveness lease (ark, OPT-IN): with a lease, this trainer's death
        # is detected by lease expiry and the servers' sync barrier
        # degrades to N-1 live trainers instead of wedging until
        # sync_timeout. Without one (default), the trainer is unknown to
        # the lease table and the legacy full-party behavior holds.
        self._heartbeat = None
        self._hb_client = None
        if heartbeat_lease_s is not None:
            from ..ark.heartbeat import HeartbeatThread
            # DEDICATED client: heartbeats must never contend with the
            # blocking sync-barrier RPC for the shared per-endpoint
            # connection, or a slow batch (longer than the lease) would
            # starve renewals and get this live trainer evicted
            self._hb_client = PSClient(transpiler._pserver_endpoints)
            self._heartbeat = HeartbeatThread(
                self._hb_client, transpiler._pserver_endpoints,
                trainer_id=self.trainer_id, session=self._session,
                lease_s=heartbeat_lease_s)
            # synchronous first beat: the lease must exist before the
            # first sync barrier so eviction semantics apply from step 0
            self._heartbeat.beat_once()
            self._heartbeat.start()

    def close(self):
        if self._heartbeat is not None:
            self._heartbeat.stop()
        if self._hb_client is not None:
            self._hb_client.close()
        super().close()

    def step(self, feed: Dict, fetch_list: Sequence) -> List[np.ndarray]:
        if _flags.get_flag("observe"):
            with _xray.span("train_step", cat="train",
                            trainer_id=self.trainer_id,
                            batch_id=self._batch_id):
                return self._step_impl(feed, fetch_list)
        return self._step_impl(feed, fetch_list)

    def _step_impl(self, feed: Dict, fetch_list: Sequence
                   ) -> List[np.ndarray]:
        # 1. recv: params as of the LAST barrier (identical on every
        # trainer — the barrier ordered the previous batch's update
        # before any release)
        self._recv_dense()

        # 2. the jitted step
        grad_fetches = [self.t.grad_names[p] for p in self.t.param_specs]
        outs = self.exe.run(self.program, feed=feed,
                            fetch_list=list(fetch_list) + grad_fetches,
                            **self._scope_kw())
        user_outs = outs[: len(fetch_list)]
        grads = outs[len(fetch_list):]

        # 3+4. send (accumulate-only pushes tagged with this trainer's
        # batch id, stable across retries — servers reject duplicates),
        # then the per-batch barrier on EVERY server; returning means
        # the aggregated update is applied. The arrival is tagged with
        # this trainer's id so an eviction of THIS trainer discounts it
        # (ark liveness). Only a successful apply advances the batch id.
        #
        # fluid-haven (replicas configured): a primary death or a
        # broken barrier mid-batch is retried INTERNALLY under the same
        # batch id — pushes dedup server-side, the client re-resolves
        # the promoted primary, and the barrier fires on the survivor —
        # so a shard failover is not a trainer-visible failure. Without
        # replicas the legacy contract holds: the error propagates and
        # the caller owns the retry.
        failover = bool(self.client.replicas)
        deadline = time.monotonic() + \
            (2.0 * self.client.failover_s if failover else 0.0)
        while True:
            try:
                self.client.push_grads_sync(self._dense_grads_by_ep(grads),
                                            batch_id=self._batch_id,
                                            trainer_id=self.trainer_id,
                                            session=self._session)
                self.client.sync_apply(self.t._pserver_endpoints,
                                       trainer_id=self.trainer_id)
                break
            except (ConnectionError, EOFError, OSError, RuntimeError) as e:
                # the retriable RuntimeErrors are the two documented
                # retry-the-step contracts: the server's barrier-reset
                # reply and the client's failed primary re-resolution —
                # anything else propagates
                retriable = isinstance(e, (ConnectionError, EOFError,
                                           OSError)) or \
                    "sync barrier broken" in str(e) or \
                    "NotPrimary" in str(e)
                if not failover or not retriable \
                        or time.monotonic() >= deadline:
                    raise
                if _flags.get_flag("observe"):
                    _metrics.counter(
                        "pserver_sync_step_retries_total",
                        "sync batches retried across a shard failover "
                        "or broken barrier").inc()
                time.sleep(0.1)
        self._batch_id += 1
        if _flags.get_flag("observe"):
            _note_step_health(user_outs, grads)
        return user_outs
