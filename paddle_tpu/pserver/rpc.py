"""Socket RPC for the host parameter-server runtime.

Capability parity with the reference's gRPC transport (reference:
paddle/fluid/operators/distributed/grpc_client.cc:66-329,
grpc_server.cc:82-415, send_recv.proto.in:20-40 `VariableMessage`).

TPU-native rationale: XLA collectives cover every *synchronous* distribution
mode, but the barrierless parameter-server mode (RunAsyncLoop,
listen_and_serv_op.cc:195) and the distributed sparse lookup table have no
collective analog — they need a host-side service. The reference vendors
gRPC+protobuf for this; here the wire format is length-prefixed pickles of
(cmd, payload) tuples over TCP — numpy arrays serialize zero-copy via
pickle protocol 5 buffers, and the stdlib socket layer keeps the runtime
dependency-free.

fluid-xray frame extension: a request frame MAY carry a third element,
a meta dict — today `{"traceparent": "00-<trace>-<span>-01"}` (W3C
trace context, observe/xray.py) — so client and server spans of one
call share a trace id across processes. The server accepts both the
2- and 3-tuple shapes (a legacy client without the field still
interoperates); a client talking to a legacy SERVER sends the plain
2-tuple (`PSClient(wire_trace=False)`, and no meta is ever attached
while the `observe` flag is off). Replies stay (status, value) 2-tuples.

fluid-wire payload extension: tensor values inside a payload MAY be
codec-tagged dicts instead of bare ndarrays (wire/codec.py — int8
per-chunk abs-max or bf16, `{"__wire__": 1, "codec": ..., "data": ...}`)
so gradient pushes and sparse-row pulls travel 2-4x smaller. The frame
layer here is codec-agnostic: tagged payloads are plain containers of
numpy arrays, already admitted by the restricted unpickler below. Raw
ndarrays remain the default wire shape — a client only sends tagged
payloads to a server that advertised them via the `wire_caps` command
(legacy servers answer unknown-command and the client degrades to raw,
the same interop posture as the xray meta element).
"""

from __future__ import annotations

import io
import pickle
import socket
import struct
from typing import Any, Tuple

_HDR = struct.Struct("!Q")  # 8-byte big-endian length prefix


class RPCConnectionError(ConnectionError):
    """The peer closed or reset the connection mid-frame. Carries the
    endpoint and the read progress so a half-delivered message surfaces
    as a diagnosable transport failure, not a bare struct.error or
    short-read EOFError (reference grpc_client.cc surfaces the endpoint
    in every failed-RPC log line for the same reason)."""


def _peer_of(sock: socket.socket) -> str:
    try:
        host, port = sock.getpeername()[:2]
        return f"{host}:{port}"
    except OSError:
        return "<disconnected>"


# test-only fault injection point (ark/chaos.py). The hook receives
# (direction, sock, wire_bytes_or_None) and returns the bytes to send
# (possibly delayed/modified), or None when it consumed or discarded the
# message itself. None hook (default) costs one attribute read per call.
_fault_hook = None


def set_fault_hook(fn) -> None:
    global _fault_hook
    _fault_hook = fn


def get_fault_hook():
    return _fault_hook

# Trust boundary: like the reference's INSECURE gRPC channels
# (grpc_client.cc creates no credentials), this transport assumes a trusted
# cluster network. Defense in depth: deserialization goes through a
# restricted unpickler that only reconstructs numpy arrays/scalars and plain
# containers, so a stray connection cannot smuggle a __reduce__ payload into
# arbitrary code execution.
_ALLOWED = {
    ("numpy", "ndarray"), ("numpy", "dtype"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.numeric", "_frombuffer"),
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy", "int32"), ("numpy", "int64"),
    ("numpy", "float32"), ("numpy", "float64"), ("numpy", "bool_"),
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        if (module, name) in _ALLOWED:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"pserver wire protocol forbids {module}.{name}")


def send_msg(sock: socket.socket, obj: Any) -> int:
    """Send one length-prefixed message; returns the wire byte count so
    observing callers can account traffic without re-serializing."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    data = _HDR.pack(len(payload)) + payload
    if _fault_hook is not None:
        data = _fault_hook("send", sock, data)
        if data is None:   # injected drop/truncate consumed the message
            return _HDR.size + len(payload)
    sock.sendall(data)
    return _HDR.size + len(payload)


def recv_msg(sock: socket.socket, with_size: bool = False) -> Any:
    """Receive one message. `with_size=True` returns (obj, wire_bytes)
    for telemetry callers; the default keeps the legacy single-value
    return."""
    if _fault_hook is not None:
        _fault_hook("recv", sock, None)
    header = _recv_exact(sock, _HDR.size, what="header")
    (n,) = _HDR.unpack(header)
    obj = _RestrictedUnpickler(
        io.BytesIO(_recv_exact(sock, n, what="payload"))).load()
    if with_size:
        return obj, _HDR.size + n
    return obj


def _recv_exact(sock: socket.socket, n: int, what: str = "message") -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise RPCConnectionError(
                f"peer {_peer_of(sock)} closed connection mid-{what}: "
                f"got {got}/{n} bytes")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def parse_endpoint(endpoint: str) -> Tuple[str, int]:
    host, port = endpoint.rsplit(":", 1)
    return host or "127.0.0.1", int(port)


def connect(endpoint: str, timeout: float = 30.0) -> socket.socket:
    host, port = parse_endpoint(endpoint)
    sock = socket.create_connection((host, port), timeout=timeout)
    # the timeout above guards only the CONNECT; replies may legitimately
    # take longer (barrier with skewed trainers, large gets) and a timeout
    # mid-exchange would desynchronize the length-prefixed stream. Dead
    # peers are detected by TCP keepalive instead of a read timeout.
    sock.settimeout(None)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    for opt, val in (("TCP_KEEPIDLE", 30), ("TCP_KEEPINTVL", 10),
                     ("TCP_KEEPCNT", 6)):
        if hasattr(socket, opt):
            sock.setsockopt(socket.IPPROTO_TCP, getattr(socket, opt), val)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock
