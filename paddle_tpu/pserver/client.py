"""Trainer-side parameter-server client + async train-step driver.

Capability parity with the reference trainer-side distributed ops
(reference: paddle/fluid/operators/send_op.cc:28, recv_op.cc, prefetch op,
operators/distributed/grpc_client.cc AsyncSendVar :66 / AsyncGetVar :122 /
AsyncPrefetchVar; split_ids/merge_ids ops for the sparse path;
python/paddle/fluid/transpiler/distribute_transpiler.py:316
`_replace_lookup_table_op_with_prefetch`).

TPU-native redesign: RPC cannot happen inside a jitted XLA step, so the
send/recv/prefetch ops become HOST-side phases around the compiled step:

    pull params -> [jitted fwd+bwd on TPU] -> push grads     (async, P3)
    prefetch rows -> [jitted step on gathered sub-table] -> push row grads (P5)

The compiled step itself is unchanged pure XLA — exactly the split the
reference makes between compute ops and distributed ops, relocated to the
host boundary where TPUs require it.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import flags as _flags
from ..observe import metrics as _metrics
from . import rpc


class PSClient:
    """Connection pool + typed calls to a set of parameter servers."""

    def __init__(self, endpoints: Sequence[str]):
        self.endpoints = list(endpoints)
        self._socks = {}
        self._lock = threading.Lock()
        self._ep_locks: Dict[str, threading.Lock] = {}
        # persistent pool: the parallel get/push run on every training step
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, len(self.endpoints)),
            thread_name_prefix="psclient")

    def _sock(self, endpoint):
        with self._lock:
            if endpoint not in self._socks:
                self._socks[endpoint] = rpc.connect(endpoint)
            return self._socks[endpoint]

    # RPCs safe to replay on a dropped connection: reads and first-wins
    # initialization. Mutating commands (push_grad, batch_barrier, ...)
    # are NOT replayed — the drop may have happened after the server
    # applied the request, and a duplicate grad push double-steps the
    # param while a duplicate barrier arrival releases it early.
    _IDEMPOTENT = frozenset({"get_param", "get_params", "prefetch_rows",
                             "init_param", "init_table"})

    def _call(self, endpoint, cmd, **payload):
        obs = _flags.get_flag("observe")
        t0 = time.perf_counter() if obs else 0.0
        tx = rx = 0
        with self._lock:
            ep_lock = self._ep_locks.setdefault(endpoint, threading.Lock())
        with ep_lock:  # one in-flight request per connection
            try:
                sock = self._sock(endpoint)
                tx = rpc.send_msg(sock, (cmd, payload))
                (status, value), rx = rpc.recv_msg(sock, with_size=True)
            except (ConnectionError, EOFError, OSError):
                if cmd not in self._IDEMPOTENT:
                    if obs:
                        _metrics.counter(
                            "pserver_client_errors_total",
                            "client RPCs failed without retry").inc(cmd=cmd)
                    raise
                # transparent one-shot reconnect for idempotent RPCs, as
                # the reference's gRPC channel re-dials dropped channels
                if obs:
                    _metrics.counter(
                        "pserver_client_retries_total",
                        "idempotent RPCs replayed after a dropped "
                        "connection").inc(cmd=cmd)
                with self._lock:
                    old = self._socks.pop(endpoint, None)
                if old is not None:
                    try:
                        old.close()
                    except OSError:
                        pass
                sock = self._sock(endpoint)
                tx = rpc.send_msg(sock, (cmd, payload))
                (status, value), rx = rpc.recv_msg(sock, with_size=True)
        if obs:
            _metrics.counter(
                "pserver_client_requests_total",
                "client RPCs by command (push/pull counts)").inc(cmd=cmd)
            _metrics.counter(
                "pserver_client_bytes_sent_total",
                "wire bytes sent to pservers").inc(tx, cmd=cmd)
            _metrics.counter(
                "pserver_client_bytes_received_total",
                "wire bytes received from pservers").inc(rx, cmd=cmd)
            _metrics.histogram(
                "pserver_client_rpc_seconds",
                "client-observed RPC latency").observe(
                    time.perf_counter() - t0, cmd=cmd)
        if status != "ok":
            raise RuntimeError(f"pserver {endpoint} {cmd}: {value}")
        return value

    # -- dense ------------------------------------------------------------
    def init_param(self, endpoint, name, value, opt_type, lr, attrs):
        self._call(endpoint, "init_param", name=name,
                   value=np.asarray(value), opt_type=opt_type, lr=lr,
                   attrs=attrs)

    def get_param(self, endpoint, name) -> np.ndarray:
        return self._call(endpoint, "get_param", name=name)

    def push_grad(self, endpoint, name, grad):
        self._call(endpoint, "push_grad", name=name, grad=np.asarray(grad))

    def _fanout(self, cmd: str, payload_by_ep: Dict[str, dict]
                ) -> Dict[str, object]:
        """One RPC per endpoint, endpoints in parallel (reference
        AsyncSendVar/AsyncGetVar handle overlap, grpc_client.cc:66/:122).
        Single-endpoint calls skip the pool."""
        if len(payload_by_ep) <= 1:
            return {ep: self._call(ep, cmd, **payload)
                    for ep, payload in payload_by_ep.items()}
        futs = {ep: self._pool.submit(self._call, ep, cmd, **payload)
                for ep, payload in payload_by_ep.items()}
        return {ep: f.result() for ep, f in futs.items()}

    def get_params_parallel(self, by_ep: Dict[str, List[str]]
                            ) -> Dict[str, Dict[str, np.ndarray]]:
        return self._fanout("get_params",
                            {ep: {"names": names}
                             for ep, names in by_ep.items()})

    def push_grads_parallel(self, by_ep: Dict[str, Dict[str, np.ndarray]]):
        self._fanout("push_grads",
                     {ep: {"grads": grads} for ep, grads in by_ep.items()})

    # -- sparse -------------------------------------------------------------
    def init_table(self, name, rows, width, dtype, init_low, init_high,
                   seed, opt_type, lr, attrs):
        """Create the row shard on every server (id % n_servers sharding)."""
        n = len(self.endpoints)
        for i, ep in enumerate(self.endpoints):
            local_rows = (rows - i + n - 1) // n  # rows with id % n == i
            self._call(ep, "init_table", name=name, local_rows=local_rows,
                       width=width, dtype=dtype, init_low=init_low,
                       init_high=init_high, seed=seed + i, opt_type=opt_type,
                       lr=lr, attrs=attrs)

    def prefetch_rows(self, name, ids: np.ndarray) -> np.ndarray:
        """Fetch rows for GLOBAL ids: split by id % n (reference
        split_ids_op), prefetch each shard, merge back in input order
        (reference merge_ids_op). ids must be non-empty (callers skip
        empty batches)."""
        ids = np.asarray(ids).reshape(-1)
        if ids.size == 0:
            raise ValueError(
                f"prefetch_rows({name!r}): empty ids — skip the prefetch "
                f"for empty batches instead")
        n = len(self.endpoints)
        out: Optional[np.ndarray] = None
        for i, ep in enumerate(self.endpoints):
            mask = (ids % n) == i
            if not mask.any():
                continue
            local = ids[mask] // n
            rows = self._call(ep, "prefetch", name=name, local_ids=local)
            if out is None:
                out = np.empty((ids.shape[0], rows.shape[1]), rows.dtype)
            out[mask] = rows
        return out

    def push_sparse_grad(self, name, ids: np.ndarray, row_grads: np.ndarray):
        ids = np.asarray(ids).reshape(-1)
        n = len(self.endpoints)
        for i, ep in enumerate(self.endpoints):
            mask = (ids % n) == i
            if not mask.any():
                continue
            self._call(ep, "push_sparse_grad", name=name,
                       local_ids=ids[mask] // n,
                       row_grads=np.asarray(row_grads)[mask])

    # -- sync mode (reference RunSyncLoop) ----------------------------------
    def push_grads_sync(self, by_ep: Dict[str, Dict[str, np.ndarray]],
                        batch_id: Optional[int] = None, trainer_id: int = 0,
                        session: Optional[str] = None):
        """Batched per-endpoint sends whose updates are DEFERRED to the
        sync_apply barrier (reference kRequestSend accumulation).
        `batch_id` must increase monotonically per trainer and stay STABLE
        across retries of the same batch — the server uses it to reject
        duplicate accumulation when a partially-failed batch is retried.
        `session` identifies the trainer PROCESS; a restarted trainer
        sends a fresh nonce so its restarted id sequence is accepted."""
        self._fanout("push_grads_sync",
                     {ep: ({"grads": grads} if batch_id is None else
                           {"grads": grads, "batch_id": int(batch_id),
                            "trainer_id": int(trainer_id),
                            "session": session})
                      for ep, grads in by_ep.items()})

    def sync_apply(self, endpoints: Sequence[str]):
        """Per-batch barrier on every server: blocks until ALL trainers
        have pushed and the aggregated update is applied (reference
        batch-barrier + optimize blocks, then kRequestGet unblocks)."""
        self._fanout("sync_apply", {ep: {} for ep in endpoints})

    # -- control ------------------------------------------------------------
    def barrier(self):
        for ep in self.endpoints:
            self._call(ep, "batch_barrier")

    def save(self, dirname):
        return [self._call(ep, "save", dirname=dirname)
                for ep in self.endpoints]

    def stop_all(self):
        for ep in self.endpoints:
            try:
                self._call(ep, "stop")
            except (RuntimeError, ConnectionError, OSError):
                pass

    def close(self):
        self._pool.shutdown(wait=False)
        with self._lock:
            for s in self._socks.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._socks.clear()
