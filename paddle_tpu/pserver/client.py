"""Trainer-side parameter-server client + async train-step driver.

Capability parity with the reference trainer-side distributed ops
(reference: paddle/fluid/operators/send_op.cc:28, recv_op.cc, prefetch op,
operators/distributed/grpc_client.cc AsyncSendVar :66 / AsyncGetVar :122 /
AsyncPrefetchVar; split_ids/merge_ids ops for the sparse path;
python/paddle/fluid/transpiler/distribute_transpiler.py:316
`_replace_lookup_table_op_with_prefetch`).

TPU-native redesign: RPC cannot happen inside a jitted XLA step, so the
send/recv/prefetch ops become HOST-side phases around the compiled step:

    pull params -> [jitted fwd+bwd on TPU] -> push grads     (async, P3)
    prefetch rows -> [jitted step on gathered sub-table] -> push row grads (P5)

The compiled step itself is unchanged pure XLA — exactly the split the
reference makes between compute ops and distributed ops, relocated to the
host boundary where TPUs require it.
"""

from __future__ import annotations

import socket as _socket
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import flags as _flags
from .. import wire as _wire
from ..ark.retry import RetryPolicy
from ..observe import flight as _flight
from ..observe import metrics as _metrics
from ..observe import xray as _xray
from . import rpc


class PSClient:
    """Connection pool + typed calls to a set of parameter servers.

    Fault tolerance (ark): every call rides a bounded exponential-backoff
    retry loop (`retry=RetryPolicy(...)`, jittered; `ark.NO_RETRY`
    restores fail-fast), honors an optional per-call wall `deadline`
    (seconds; None keeps the legacy block-forever behavior needed by the
    sync barrier), transparently reconnects sockets that went stale
    across a pserver restart, and — for read-only commands — fails over
    to replica endpoints (`replicas={primary: [backup, ...]}`) when the
    primary is gone.

    Wire compression (fluid-wire): `comm_quant="int8"|"bf16"` sends
    gradient pushes as codec-tagged payloads (wire/codec.py) with
    per-tensor client-side error feedback, and moves sparse-table rows
    quantized in both directions. The codec is NEGOTIATED per endpoint
    (one `wire_caps` RPC, cached): a legacy server that answers with an
    unknown-command error gets raw payloads — never corrupted frames —
    mirroring the xray 2-tuple/3-tuple interop posture. Default None
    keeps the wire byte-identical to pre-wire traffic."""

    def __init__(self, endpoints: Sequence[str],
                 retry: Optional[RetryPolicy] = None,
                 deadline: Optional[float] = None,
                 replicas: Optional[Dict[str, Sequence[str]]] = None,
                 wire_trace: bool = True,
                 comm_quant: Optional[str] = None,
                 read_only: bool = False,
                 dedup_pushes: bool = False,
                 trainer_id: int = 0,
                 failover_s: float = 20.0,
                 quorum_endpoints: Optional[Sequence[str]] = None,
                 quorum_resources: Optional[Dict[str, str]] = None):
        # fluid-fleet: a serving replica's sparse read path holds a
        # PSClient purely to PULL rows — read_only=True makes a mutating
        # call (a stray push_grad from a serving process would corrupt
        # live training state) unrepresentable rather than a code-review
        # promise. wire_caps stays allowed: negotiation is how the pull
        # path gets its codec.
        self.read_only = bool(read_only)
        # fluid-xray: with `wire_trace` (and the `observe` flag on) each
        # request frame carries a traceparent meta element so the server's
        # handler span joins this client's trace. False restores the bare
        # 2-tuple frame for legacy servers that reject a third element.
        self.wire_trace = bool(wire_trace)
        cq = None if comm_quant in (None, "raw") else str(comm_quant)
        if cq is not None and cq not in _wire.CODECS:
            raise _wire.WireCodecError(
                f"comm_quant must be one of {_wire.CODECS} or None, got "
                f"{comm_quant!r}")
        self.comm_quant = cq
        self._feedback = _wire.ErrorFeedback()
        self._wire_ok: Dict[str, bool] = {}   # endpoint -> negotiated?
        # endpoint -> monotonic time before which an unreachable
        # negotiation verdict is not retried (raw in the meantime)
        self._wire_retry_at: Dict[str, float] = {}
        self.endpoints = list(endpoints)
        self.retry = retry if retry is not None else RetryPolicy()
        self.deadline = deadline if deadline is not None \
            else self.retry.deadline
        self.replicas = {ep: list(reps)
                         for ep, reps in (replicas or {}).items()}
        # fluid-haven: logical endpoint -> CURRENT primary. Writes (and
        # reads) are routed through this map; it moves on a redirect
        # reply or a successful `_resolve_primary` poll after a primary
        # death. `failover_s` bounds how long a write waits for the
        # backup's lease-expiry promotion before giving up.
        self._primaries: Dict[str, str] = {}
        self.failover_s = float(failover_s)
        # fluid-quorum: when the shard's election runs through an
        # arbiter group, the client can ask the ARBITERS who rules
        # (`quorum_resources` maps a logical endpoint to its lease
        # resource; the holder id is the primary's endpoint by
        # convention) — failover then finds a primary living at an
        # endpoint no configured candidate names, without waiting out
        # the haven_role poll grid. Lazy: no arbiter RPC until the
        # first failover needs one.
        self._quorum_eps = list(quorum_endpoints or ())
        self._quorum_resources = dict(quorum_resources or {})
        self._quorum_client = None
        # fluid-haven exactly-once for BARRIERLESS pushes: when armed,
        # push_grad(s)/push_sparse_grad carry (trainer, seq, session) so
        # the server's async watermark makes them replay-safe — the rule
        # that lets a push retried at a promoted backup never
        # double-apply. Off by default: the wire stays byte-identical.
        self.dedup_pushes = bool(dedup_pushes)
        self.trainer_id = int(trainer_id)
        import uuid
        self._session = uuid.uuid4().hex
        self._push_seq = 0
        self._push_seq_lock = threading.Lock()
        self._socks = {}
        self._lock = threading.Lock()
        self._ep_locks: Dict[str, threading.Lock] = {}
        # persistent pool: the parallel get/push run on every training step
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, len(self.endpoints)),
            thread_name_prefix="psclient")

    def _drop_sock(self, endpoint):
        with self._lock:
            old = self._socks.pop(endpoint, None)
        if old is not None:
            try:
                old.close()
            except OSError:
                pass

    @staticmethod
    def _stale(sock) -> bool:
        """A cached socket whose peer restarted delivers EOF/RST on next
        use; probe with a non-blocking MSG_PEEK so the reconnect happens
        BEFORE the request is sent — otherwise a non-replayable command
        is poisoned by a server that never saw it. The protocol is
        strict request/reply, so any readable byte here is itself a
        desync; only BlockingIOError (nothing to read) means healthy."""
        try:
            sock.setblocking(False)
            try:
                return sock.recv(1, _socket.MSG_PEEK) is not None
            finally:
                sock.setblocking(True)
        except (BlockingIOError, InterruptedError):
            try:
                sock.setblocking(True)
            except OSError:
                return True
            return False
        except OSError:
            return True

    def _sock(self, endpoint, connect_timeout=None):
        with self._lock:
            sock = self._socks.get(endpoint)
        if sock is not None and self._stale(sock):
            self._drop_sock(endpoint)
            sock = None
        if sock is None:
            sock = rpc.connect(endpoint,
                               timeout=(connect_timeout
                                        if connect_timeout is not None
                                        else 30.0))
            with self._lock:
                self._socks[endpoint] = sock
        return sock

    # RPCs safe to REPLAY after the request may have reached the server:
    # reads, first-wins initialization, and batch-id-tagged sync pushes
    # (the server's (trainer, batch, session) watermark acknowledges a
    # duplicate without re-accumulating). Other mutating commands
    # (push_grad, sync_apply, batch_barrier ...) are never replayed past
    # a fully-sent request — a duplicate grad push double-steps the param
    # and a duplicate barrier arrival releases it early. They DO retry
    # send-phase failures: the frame is length-prefixed, so a request
    # whose send failed was never dispatched by the server.
    _IDEMPOTENT = frozenset({"get_param", "get_params", "prefetch",
                             "init_param", "init_table", "stats",
                             "heartbeat", "save", "restore", "wire_caps",
                             # fluid-haven: replicate dedups by seq, sync
                             # replaces state wholesale, promote fences
                             # by epoch, role is a read
                             "haven_role", "haven_replicate",
                             "haven_sync", "haven_promote"})

    # strictly read-only commands: the ONLY ones allowed to fail over to
    # a replica endpoint. Idempotent-but-mutating commands (save,
    # init_param, ...) must not — a `save` answered by a replica would
    # commit the WRONG shard into a checkpoint that verifies clean, and
    # a heartbeat lease belongs to one specific server.
    _READ_ONLY = frozenset({"get_param", "get_params", "prefetch",
                            "stats"})

    @classmethod
    def _replayable(cls, cmd, payload) -> bool:
        if cmd in cls._IDEMPOTENT:
            return True
        if cmd == "push_grads_sync":
            return payload.get("batch_id") is not None
        # fluid-haven: tagged barrierless pushes dedup server-side on
        # (trainer, seq, session) — replay-safe, including at a
        # promoted backup after a primary failover
        return cmd in ("push_grad", "push_grads", "push_sparse_grad") \
            and payload.get("seq") is not None

    # commands that legitimately block for a long time (barriers): a
    # default deadline would break them, so only an explicit per-call
    # deadline applies
    _NO_DEFAULT_DEADLINE = frozenset({"sync_apply", "batch_barrier"})

    # commands a read_only client may issue: the read set plus the
    # negotiation/introspection commands that mutate nothing server-side
    # (haven_role is how a serve-time client re-resolves a shard's
    # primary after a redirect)
    _READ_ONLY_ALLOWED = frozenset({"get_param", "get_params", "prefetch",
                                    "stats", "wire_caps", "haven_role"})

    def _phys(self, endpoint: str) -> str:
        """The physical endpoint currently serving logical `endpoint` —
        identity until a haven failover/redirect moves the mapping."""
        return self._primaries.get(endpoint, endpoint)

    def _resolve_primary(self, endpoint, wait: bool = True) -> bool:
        """Re-resolve which member of `endpoint`'s replica group is the
        PRIMARY by polling `haven_role` on every member; with `wait`,
        keep polling up to `failover_s` so a backup's lease-expiry
        promotion has time to land. Returns True when the mapping
        moved.

        Eligibility is deliberately asymmetric: the ORIGINAL endpoint
        counts as the writer whatever it answers (haven primary, solo,
        or a pre-haven server that rejects the command — it IS its
        shard's only writer), but a REPLICA member only wins with an
        explicit `role == "primary"` — a legacy read-replica listed for
        read failover must never be adopted as a write target. Waiting
        is justified only while some member reports `role == "backup"`
        (a standby that may still promote); against a plain dead server
        with legacy replicas this returns immediately."""
        cands = []
        for ep in [self._phys(endpoint), endpoint,
                   *self.replicas.get(endpoint, ())]:
            if ep not in cands:
                cands.append(ep)
        hinted = self._quorum_holder(endpoint)
        if hinted and hinted not in cands:
            # the arbiters' view leads the poll: the quorum holder is
            # the primary by construction (it may live at an endpoint
            # no configured candidate names), but it is still VERIFIED
            # below via haven_role — a stale minority view must not
            # route writes on its own
            cands.insert(0, hinted)
        deadline = time.monotonic() + (self.failover_s if wait else 0.0)
        while True:
            best, saw_standby, hints = None, False, []
            for ep in cands:
                try:
                    (status, value), _tx, _rx = self._call_one(
                        ep, "haven_role", {}, 1.0, False, None)
                except (ConnectionError, EOFError, OSError):
                    continue
                if status == "ok":
                    role = value.get("role")
                    epoch = value.get("epoch", -1)
                    # a standby/retired member ADVERTISES its primary:
                    # after a handover to a brand-new endpoint no
                    # configured candidate may be the primary at all —
                    # the hint is the only road to it
                    hint = value.get("primary")
                    if hint and hint not in cands and hint not in hints:
                        hints.append(hint)
                elif status == "err" and \
                        "unknown pserver command" in str(value):
                    role, epoch = "solo", -1   # pre-haven server
                else:
                    continue
                if role == "backup":
                    saw_standby = True
                    continue
                if role == "primary" or \
                        (role == "solo" and ep == endpoint):
                    if best is None or epoch > best[1]:
                        best = (ep, epoch)
            if best is None and hints:
                cands.extend(hints)
                continue   # poll the advertised primary immediately
            if best is not None:
                new = best[0]
                changed = new != self._phys(endpoint)
                if changed:
                    if new == endpoint:
                        self._primaries.pop(endpoint, None)
                    else:
                        self._primaries[endpoint] = new
                    _flight.note("haven_resolved", endpoint=endpoint,
                                 primary=new, epoch=best[1])
                return changed
            if not wait or not saw_standby \
                    or time.monotonic() >= deadline:
                return False
            time.sleep(0.25)

    def _quorum_holder(self, endpoint) -> Optional[str]:
        """Ask the arbiter group who holds `endpoint`'s shard lease
        (None without a quorum route, on a minority view, or when no
        arbiter answers)."""
        resource = self._quorum_resources.get(endpoint)
        if resource is None or not self._quorum_eps:
            return None
        if self._quorum_client is None:
            from ..quorum import QuorumClient
            with self._lock:
                if self._quorum_client is None:
                    self._quorum_client = QuorumClient(self._quorum_eps,
                                                       deadline_s=1.0)
        try:
            rec = self._quorum_client.holder(resource)
        except Exception:   # noqa: BLE001 — resolution is best-effort
            return None
        return rec["holder"] if rec else None

    def _call(self, endpoint, cmd, _deadline=..., **payload):
        """One logical RPC with retry/backoff/deadline; `_deadline=...`
        (unset) follows the client default, None disables, a float
        overrides.

        fluid-haven routing: the call targets the shard's CURRENT
        primary (`self._primaries`). A `redirect` reply (standby backup
        or retired server) moves the mapping and retries — the redirect
        preceded dispatch, so ANY command is safe to reissue. A
        transport failure of every member extends the old read-only
        failover rule to WRITES: for replay-safe commands (reads,
        first-wins inits, batch-tagged sync pushes, seq-tagged async
        pushes) the client re-resolves the primary — polling
        `haven_role` while the backup's lease-expiry promotion lands —
        and replays there; the server-side (trainer, batch/seq, nonce)
        watermarks make the replay exactly-once even when the dead
        primary had already applied and replicated it."""
        if self.read_only and cmd not in self._READ_ONLY_ALLOWED:
            raise RuntimeError(
                f"PSClient(read_only=True) refuses mutating command "
                f"{cmd!r} — the serve-time sparse read path may only "
                f"{sorted(self._READ_ONLY_ALLOWED)}")
        if _deadline is ...:
            _deadline = (None if cmd in self._NO_DEFAULT_DEADLINE
                         else self.deadline)
        obs = _flags.get_flag("observe")
        t0 = time.perf_counter() if obs else 0.0
        # fluid-xray call context: ONE span for the logical call (child of
        # the ambient trace, or the root of a fresh one). Every attempt —
        # retries AND replica failovers — parents to it, so retries share
        # the trace id with a new span per attempt, and a failover keeps
        # the same parent span.
        call_ctx = _xray.child_of() if obs else None
        ts_wall = time.time() if obs else 0.0
        served_ep, call_outcome = endpoint, "failed"
        status, value, tx, rx = "err", "unresolved", 0, 0
        try:
            for _hop in range(4):
                primary = self._phys(endpoint)
                candidates = [primary]
                if cmd in self._READ_ONLY:
                    candidates += [
                        ep for ep in ([endpoint]
                                      + self.replicas.get(endpoint, []))
                        if ep not in candidates]
                last_err = None
                reply = None
                for i, ep in enumerate(candidates):
                    try:
                        reply, tx, rx = self._call_one(
                            ep, cmd, payload, _deadline, obs, call_ctx)
                        served_ep = ep
                        break
                    except (ConnectionError, EOFError, OSError) as e:
                        last_err = e
                        if i + 1 < len(candidates) and obs:
                            _metrics.counter(
                                "pserver_client_failovers_total",
                                "reads rerouted to a replica "
                                "endpoint").inc(cmd=cmd, frm=ep)
                            _flight.note("rpc_failover", cmd=cmd, frm=ep,
                                         to=candidates[i + 1],
                                         error=type(e).__name__)
                        continue
                if reply is None:
                    # every member transport-failed: a replay-safe call
                    # against a haven pair waits out the promotion and
                    # replays at the re-resolved primary
                    if self.replicas.get(endpoint) and \
                            self._replayable(cmd, payload) and \
                            self._resolve_primary(
                                endpoint, wait=cmd != "heartbeat"):
                        if obs:
                            _metrics.counter(
                                "pserver_client_primary_failovers_total",
                                "calls replayed at a re-resolved shard "
                                "primary").inc(cmd=cmd)
                        _flight.note("haven_failover", cmd=cmd,
                                     frm=primary,
                                     to=self._phys(endpoint))
                        continue
                    if obs:
                        _flight.note("rpc_outcome", cmd=cmd,
                                     endpoint=endpoint, outcome="failed",
                                     error=type(last_err).__name__)
                    raise last_err
                status, value = reply
                if status == "redirect":
                    new = (value or {}).get("primary")
                    moved = False
                    if new and new != self._phys(endpoint):
                        self._primaries[endpoint] = new
                        moved = True
                    elif self.replicas.get(endpoint) or not new:
                        moved = self._resolve_primary(endpoint)
                    if moved:
                        if obs:
                            _metrics.counter(
                                "pserver_client_primary_failovers_total",
                                "calls replayed at a re-resolved shard "
                                "primary").inc(cmd=cmd)
                        _flight.note("haven_redirect", cmd=cmd,
                                     frm=served_ep,
                                     to=self._phys(endpoint))
                        continue
                    status, value = "err", \
                        f"NotPrimary: no reachable primary ({value})"
                call_outcome = "ok" if status == "ok" else "err_reply"
                break
            else:
                status, value = "err", ("redirect loop: the shard's "
                                        "primary keeps moving")
        finally:
            # attribute the logical call to the endpoint that actually
            # served it (after a failover that is the replica, not the
            # dead primary) and tag how it ended — a postmortem timeline
            # read top-down must not show a failed/rerouted call as a
            # clean success on the primary
            if call_ctx is not None:
                _xray.record_span(f"ps_call:{cmd}", call_ctx, ts_wall,
                                  time.perf_counter() - t0, cat="rpc",
                                  cmd=cmd, endpoint=served_ep,
                                  outcome=call_outcome)
        if obs:
            _metrics.counter(
                "pserver_client_requests_total",
                "client RPCs by command (push/pull counts)").inc(cmd=cmd)
            _metrics.counter(
                "pserver_client_bytes_sent_total",
                "wire bytes sent to pservers").inc(tx, cmd=cmd)
            _metrics.counter(
                "pserver_client_bytes_received_total",
                "wire bytes received from pservers").inc(rx, cmd=cmd)
            _metrics.histogram(
                "pserver_client_rpc_seconds",
                "client-observed RPC latency").observe(
                    time.perf_counter() - t0, cmd=cmd)
        if status != "ok":
            if obs:
                _flight.note("rpc_outcome", cmd=cmd, endpoint=endpoint,
                             outcome="err_reply", error=str(value)[:200])
            raise RuntimeError(f"pserver {endpoint} {cmd}: {value}")
        return value

    def _call_one(self, endpoint, cmd, payload, deadline, obs,
                  call_ctx=None):
        """The per-endpoint retry loop. Failure phases:

        - connect/send: the length-prefixed frame never reached the
          server complete, so it was never dispatched — ANY command is
          safe to retry;
        - recv (incl. a deadline timeout): the server may have applied
          the request — only replayable commands retry.

        fluid-xray: every ATTEMPT gets its own span (a fresh child of
        `call_ctx`, so retries and failovers share one trace id with a
        distinct span id per attempt); the attempt's context rides the
        frame as a traceparent meta element, making the server handler
        span its child.
        """
        policy = self.retry
        replay_ok = self._replayable(cmd, payload)
        deadline_at = None if deadline is None \
            else time.monotonic() + deadline
        with self._lock:
            ep_lock = self._ep_locks.setdefault(endpoint, threading.Lock())
        attempt = 0
        with ep_lock:  # one in-flight request per connection
            while True:
                phase = "connect"
                att_ctx = call_ctx.child() if call_ctx is not None else None
                att_ts = time.time() if obs else 0.0
                att_t0 = time.perf_counter() if obs else 0.0

                def _att_span(outcome):
                    if att_ctx is not None:
                        _xray.record_span(
                            f"rpc_client:{cmd}", att_ctx, att_ts,
                            time.perf_counter() - att_t0, cat="rpc",
                            cmd=cmd, endpoint=endpoint, attempt=attempt,
                            outcome=outcome)
                try:
                    # the connect itself honors the remaining deadline:
                    # rpc.connect's default 30 s would otherwise wedge a
                    # short-deadline call (heartbeats!) on a blackholed
                    # endpoint for 30 s per attempt
                    remaining = None if deadline_at is None else \
                        max(0.01, deadline_at - time.monotonic())
                    sock = self._sock(endpoint, connect_timeout=remaining)
                    if deadline_at is not None:
                        sock.settimeout(
                            max(0.01, deadline_at - time.monotonic()))
                    phase = "send"
                    frame = (cmd, payload)
                    if att_ctx is not None and self.wire_trace:
                        frame = (cmd, payload, _xray.to_wire(att_ctx))
                    tx = rpc.send_msg(sock, frame)
                    phase = "recv"
                    reply, rx = rpc.recv_msg(sock, with_size=True)
                    if deadline_at is not None:
                        sock.settimeout(None)
                    _att_span("ok")
                    return reply, tx, rx
                except (ConnectionError, EOFError, OSError):
                    _att_span(f"fail_{phase}")
                    self._drop_sock(endpoint)
                    safe = phase != "recv" or replay_ok
                    out_of_time = deadline_at is not None and \
                        time.monotonic() >= deadline_at
                    if not safe or attempt >= policy.max_attempts \
                            or out_of_time:
                        if obs:
                            _metrics.counter(
                                "pserver_client_gave_up_total",
                                "RPCs abandoned after exhausting retries "
                                "(or unsafe to replay)").inc(
                                    cmd=cmd, phase=phase)
                            _flight.note("rpc_gave_up", cmd=cmd,
                                         endpoint=endpoint, phase=phase,
                                         attempts=attempt + 1)
                        raise
                    if obs:
                        _metrics.counter(
                            "pserver_client_retries_total",
                            "RPC attempts replayed after a transport "
                            "failure").inc(cmd=cmd, phase=phase)
                        _flight.note("rpc_retry", cmd=cmd,
                                     endpoint=endpoint, phase=phase,
                                     attempt=attempt)
                    delay = policy.backoff(attempt)
                    attempt += 1
                    if deadline_at is not None:
                        delay = min(delay,
                                    max(0.0, deadline_at - time.monotonic()))
                    if delay:
                        time.sleep(delay)

    # -- wire codec (fluid-wire) ------------------------------------------
    def _codec_for(self, endpoint) -> Optional[str]:
        """The codec to use toward `endpoint`: `comm_quant` when the
        server advertises it (one cached `wire_caps` RPC per endpoint),
        else None (raw). A legacy server answers `wire_caps` with an
        unknown-command error reply — negotiate down to raw instead of
        feeding tagged payloads to handlers that would misread them."""
        if self.comm_quant is None:
            return None
        ok = self._wire_ok.get(endpoint)
        if ok is None:
            if self._wire_retry_at.get(endpoint, 0.0) > time.monotonic():
                return None   # recent unreachable verdict: raw, no probe
            outcome = "ok"
            try:
                # short-deadline probe: with the endpoint down, the probe
                # must not burn the full retry/backoff budget in front of
                # every call that could itself fail over to a replica
                caps = self._call(endpoint, "wire_caps", _deadline=2.0)
                ok = self.comm_quant in (caps or {}).get("codecs", ())
                if not ok:
                    outcome = "unsupported_codec"
            except RuntimeError as e:
                if "unknown pserver command" not in str(e):
                    raise
                ok, outcome = False, "legacy_raw"
            except (ConnectionError, EOFError, OSError):
                # the endpoint is unreachable right now: degrade THIS call
                # to raw instead of raising — negotiation must never cost
                # availability. In particular a READ against a dead
                # primary still reaches its replica: the prefetch itself
                # fails over (wire_caps deliberately does NOT — a
                # replica's caps must not be attributed to the primary's
                # endpoint key). Unlike legacy_raw/unsupported_codec this
                # verdict is NOT cached: a transient failure (pserver
                # restart mid-session — ark reconnects through those)
                # must not silently disable compression for the rest of
                # the session. A short cooldown amortizes the probe so a
                # long outage doesn't pay it in front of every call.
                ok, outcome = None, "unreachable"
                self._wire_retry_at[endpoint] = time.monotonic() + 30.0
            if ok is not None:
                self._wire_ok[endpoint] = ok
            if _flags.get_flag("observe"):
                _metrics.counter(
                    "pserver_wire_negotiations_total",
                    "wire-codec negotiations per endpoint (legacy servers "
                    "degrade to raw)").inc(endpoint=endpoint,
                                           codec=self.comm_quant,
                                           outcome=outcome)
        return self.comm_quant if ok else None

    def wire_state(self):
        """Error-feedback residuals as npz-compatible arrays — merge into
        an ark checkpoint's `arrays` and hand back to
        `restore_wire_state` after resume to keep pushes bit-identical
        to the uninterrupted run under `comm_quant` (the residual is
        trainer-local, so the server-side shard snapshot cannot carry
        it; see docs/COMMUNICATION.md §Checkpointing)."""
        return self._feedback.state_dict()

    def restore_wire_state(self, state) -> None:
        self._feedback.load_state_dict(state)

    @staticmethod
    def _account_wire(cmd, raw_nbytes, enc_nbytes):
        """Raw vs on-wire tensor bytes per command: compression ratio is
        a first-class metric (observe-gated like every runtime emitter)."""
        if not _flags.get_flag("observe"):
            return
        _metrics.counter(
            _wire.RAW_BYTES_METRIC,
            "tensor payload bytes before the wire codec, per command").inc(
                raw_nbytes, cmd=cmd)
        _metrics.counter(
            _wire.ENCODED_BYTES_METRIC,
            "tensor payload bytes after the wire codec (on-wire), per "
            "command").inc(enc_nbytes, cmd=cmd)

    def _push_grads_one(self, endpoint, cmd, grads, extra=None):
        """Encode (negotiated codec + error feedback) and send one
        per-endpoint grads dict. Residuals commit only after the call
        returns — transport retries resend the SAME encoded bytes and a
        caller-level retry re-encodes from the unchanged residual, so a
        replayed frame can never double-apply feedback (wire/feedback.py
        replay contract, drilled by chaos `quant_flaky_rpc`)."""
        codec = self._codec_for(endpoint)
        # sync pushes carry a (session, batch) identity: the residual
        # commit dedups on it, exactly like the server's accumulation
        tag = None
        if extra and extra.get("batch_id") is not None:
            tag = (extra.get("session"), extra.get("trainer_id"),
                   extra["batch_id"])
        wire_grads, commits = {}, []
        raw_b = enc_b = 0
        for name, g in grads.items():
            g = np.asarray(g)
            raw_b += g.nbytes
            if codec is None or g.dtype != np.float32:
                wire_grads[name] = g
                enc_b += g.nbytes
            else:
                payload, commit = self._feedback.encode(
                    (endpoint, name), g, codec, name=name, tag=tag)
                wire_grads[name] = payload
                enc_b += _wire.payload_nbytes(payload)
                commits.append(commit)
        self._account_wire(cmd, raw_b, enc_b)
        out = self._call(endpoint, cmd, grads=wire_grads, **(extra or {}))
        for commit in commits:
            commit()
        return out

    def _push_tag(self) -> Optional[dict]:
        """(seq, trainer, session) identity for ONE tagged barrierless
        push (fluid-haven). The seq is assigned once per logical push
        and stays stable across transport retries AND primary
        failovers, so the server-side async watermark acknowledges a
        replay without re-applying. Seqs are monotone per endpoint
        because a trainer issues its pushes sequentially (the
        per-endpoint fanout parallelism never races two pushes to one
        endpoint)."""
        if not self.dedup_pushes:
            return None
        with self._push_seq_lock:
            self._push_seq += 1
            return {"seq": self._push_seq, "trainer_id": self.trainer_id,
                    "session": self._session}

    # -- dense ------------------------------------------------------------
    def init_param(self, endpoint, name, value, opt_type, lr, attrs):
        self._call(endpoint, "init_param", name=name,
                   value=np.asarray(value), opt_type=opt_type, lr=lr,
                   attrs=attrs)

    def get_param(self, endpoint, name) -> np.ndarray:
        return self._call(endpoint, "get_param", name=name)

    def push_grad(self, endpoint, name, grad):
        grad = np.asarray(grad)
        tag = self._push_tag() or {}
        codec = self._codec_for(endpoint)
        if codec is None or grad.dtype != np.float32:
            self._account_wire("push_grad", grad.nbytes, grad.nbytes)
            self._call(endpoint, "push_grad", name=name, grad=grad, **tag)
            return
        payload, commit = self._feedback.encode((endpoint, name), grad,
                                                codec, name=name)
        self._account_wire("push_grad", grad.nbytes,
                           _wire.payload_nbytes(payload))
        self._call(endpoint, "push_grad", name=name, grad=payload, **tag)
        commit()

    def _fanout_each(self, calls: Dict[str, object]) -> Dict[str, object]:
        """Run one thunk per endpoint, endpoints in parallel (reference
        AsyncSendVar/AsyncGetVar handle overlap, grpc_client.cc:66/:122).
        Single-endpoint calls skip the pool."""
        if len(calls) <= 1:
            return {ep: fn() for ep, fn in calls.items()}
        futs = {ep: self._pool.submit(fn) for ep, fn in calls.items()}
        return {ep: f.result() for ep, f in futs.items()}

    def _fanout(self, cmd: str, payload_by_ep: Dict[str, dict]
                ) -> Dict[str, object]:
        return self._fanout_each(
            {ep: (lambda ep=ep, payload=payload:
                  self._call(ep, cmd, **payload))
             for ep, payload in payload_by_ep.items()})

    def get_params_parallel(self, by_ep: Dict[str, List[str]]
                            ) -> Dict[str, Dict[str, np.ndarray]]:
        return self._fanout("get_params",
                            {ep: {"names": names}
                             for ep, names in by_ep.items()})

    def push_grads_parallel(self, by_ep: Dict[str, Dict[str, np.ndarray]]):
        self._fanout_each(
            {ep: (lambda ep=ep, grads=grads, tag=self._push_tag():
                  self._push_grads_one(ep, "push_grads", grads, tag))
             for ep, grads in by_ep.items()})

    # -- sparse -------------------------------------------------------------
    def init_table(self, name, rows, width, dtype, init_low, init_high,
                   seed, opt_type, lr, attrs):
        """Create the row shard on every server (id % n_servers sharding)."""
        n = len(self.endpoints)
        for i, ep in enumerate(self.endpoints):
            local_rows = (rows - i + n - 1) // n  # rows with id % n == i
            self._call(ep, "init_table", name=name, local_rows=local_rows,
                       width=width, dtype=dtype, init_low=init_low,
                       init_high=init_high, seed=seed + i, opt_type=opt_type,
                       lr=lr, attrs=attrs)

    def prefetch_rows(self, name, ids: np.ndarray) -> np.ndarray:
        """Fetch rows for GLOBAL ids: split by id % n (reference
        split_ids_op), prefetch each shard, merge back in input order
        (reference merge_ids_op). ids must be non-empty (callers skip
        empty batches). With `comm_quant` negotiated, the reply rows
        arrive quantized (the embedding-row pull is the DeepFM-shape
        bandwidth hog) and are decoded here."""
        ids = np.asarray(ids).reshape(-1)
        if ids.size == 0:
            raise ValueError(
                f"prefetch_rows({name!r}): empty ids — skip the prefetch "
                f"for empty batches instead")
        n = len(self.endpoints)
        out: Optional[np.ndarray] = None
        for i, ep in enumerate(self.endpoints):
            mask = (ids % n) == i
            if not mask.any():
                continue
            local = ids[mask] // n
            codec = self._codec_for(ep)
            kwargs = dict(name=name, local_ids=local)
            if codec is not None:
                kwargs["codec"] = codec
            try:
                reply = self._call(ep, "prefetch", **kwargs)
            except RuntimeError as e:
                # degrade-on-evidence: prefetch is read-only and may have
                # FAILED OVER to a replica that never negotiated — a
                # pre-wire replica rejects the codec kwarg with a
                # TypeError reply. Retry bare (raw is correct against
                # every version) instead of surfacing a hard failure from
                # a healthy replica, and DROP the cached verdict rather
                # than pinning the endpoint raw: the reply may have come
                # from the replica, and a replica's (lack of) caps must
                # not be attributed to the primary's endpoint key. The
                # next call re-negotiates wire_caps against the primary
                # itself — a healthy wire-aware primary gets compression
                # back, a genuinely legacy peer caches legacy_raw there.
                if "codec" not in kwargs or \
                        "keyword argument" not in str(e) or \
                        "codec" not in str(e):
                    raise
                self._wire_ok.pop(ep, None)
                del kwargs["codec"]
                reply = self._call(ep, "prefetch", **kwargs)
            rows = _wire.maybe_decode(reply)
            self._account_wire("prefetch", rows.nbytes,
                               _wire.payload_nbytes(reply))
            if out is None:
                out = np.empty((ids.shape[0], rows.shape[1]), rows.dtype)
            out[mask] = rows
        return out

    def push_sparse_grad(self, name, ids: np.ndarray, row_grads: np.ndarray):
        """Scatter row gradients to their shards; with `comm_quant`
        negotiated the rows travel int8/bf16 (no error feedback on the
        sparse path: the touched-row set changes every batch, so there is
        no per-tensor residual stream to carry — abs-max per chunk keeps
        the row update error at half an lsb)."""
        ids = np.asarray(ids).reshape(-1)
        n = len(self.endpoints)
        for i, ep in enumerate(self.endpoints):
            mask = (ids % n) == i
            if not mask.any():
                continue
            sub = np.asarray(row_grads)[mask]
            codec = self._codec_for(ep)
            payload = sub
            if codec is not None and sub.dtype == np.float32:
                payload = _wire.encode_tensor(sub, codec, name=name)
            self._account_wire("push_sparse_grad", sub.nbytes,
                               _wire.payload_nbytes(payload))
            self._call(ep, "push_sparse_grad", name=name,
                       local_ids=ids[mask] // n, row_grads=payload,
                       **(self._push_tag() or {}))

    # -- sync mode (reference RunSyncLoop) ----------------------------------
    def push_grads_sync(self, by_ep: Dict[str, Dict[str, np.ndarray]],
                        batch_id: Optional[int] = None, trainer_id: int = 0,
                        session: Optional[str] = None):
        """Batched per-endpoint sends whose updates are DEFERRED to the
        sync_apply barrier (reference kRequestSend accumulation).
        `batch_id` must increase monotonically per trainer and stay STABLE
        across retries of the same batch — the server uses it to reject
        duplicate accumulation when a partially-failed batch is retried.
        `session` identifies the trainer PROCESS; a restarted trainer
        sends a fresh nonce so its restarted id sequence is accepted."""
        extra = {} if batch_id is None else {
            "batch_id": int(batch_id), "trainer_id": int(trainer_id),
            "session": session}
        self._fanout_each(
            {ep: (lambda ep=ep, grads=grads:
                  self._push_grads_one(ep, "push_grads_sync", grads,
                                       dict(extra)))
             for ep, grads in by_ep.items()})

    def sync_apply(self, endpoints: Sequence[str],
                   trainer_id: Optional[int] = None):
        """Per-batch barrier on every server: blocks until ALL trainers
        have pushed and the aggregated update is applied (reference
        batch-barrier + optimize blocks, then kRequestGet unblocks).
        `trainer_id` identifies this arrival to the evicting barrier so
        a later eviction of THIS trainer discounts its arrival (ark
        liveness); untagged arrivals keep the legacy anonymous count."""
        payload = {} if trainer_id is None else \
            {"trainer_id": int(trainer_id)}
        self._fanout("sync_apply", {ep: dict(payload) for ep in endpoints})

    # -- control ------------------------------------------------------------
    def heartbeat(self, endpoint, trainer_id, session=None,
                  lease_s: float = 3.0):
        """Renew this trainer's liveness lease on `endpoint` (ark).
        Short deadline: a wedged server must not wedge the heartbeat
        loop — the whole point is detecting exactly that."""
        return self._call(endpoint, "heartbeat",
                          _deadline=min(lease_s, 2.0),
                          trainer_id=int(trainer_id), session=session,
                          lease_s=float(lease_s))

    def barrier(self):
        for ep in self.endpoints:
            self._call(ep, "batch_barrier")

    def save(self, dirname):
        return [self._call(ep, "save", dirname=dirname)
                for ep in self.endpoints]

    def stop_all(self):
        for ep in self.endpoints:
            try:
                self._call(ep, "stop")
            except (RuntimeError, ConnectionError, OSError):
                pass

    def close(self):
        self._pool.shutdown(wait=False)
        if self._quorum_client is not None:
            try:
                self._quorum_client.close()
            except Exception:
                pass
        with self._lock:
            for s in self._socks.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._socks.clear()
