"""Host parameter-server runtime: async (barrierless) updates + distributed
sparse lookup tables — the two reference capabilities with no XLA-collective
analog (reference: listen_and_serv_op.cc RunAsyncLoop :195,
doc/fluid/design/dist_train/distributed_lookup_table_design.md).
Sync modes never come here: they collapse to GSPMD collectives
(transpiler/distribute_transpiler.py)."""

from .server import ParameterServer  # noqa: F401
from .client import PSClient  # noqa: F401
from .trainer import AsyncPSTrainer, SyncPSTrainer  # noqa: F401
