"""Server-side optimizer kernels (numpy, dense + per-row sparse).

Capability parity with the reference pserver's per-param optimize blocks
(reference: python/paddle/fluid/transpiler/distribute_transpiler.py:333
`get_pserver_program` builds one optimize sub-block per param slice;
operators' SelectedRows kernels, e.g. paddle/fluid/operators/sgd_op.h:63,
adam_op.h sparse path, apply row-wise updates for sparse grads).

The host PS runs on CPU; numpy is the natural kernel substrate (the
reference's pserver optimize blocks likewise run CPU Eigen kernels). Each
optimizer holds its accumulators keyed like the reference's
`_create_accumulators`, and exposes `dense(param, grad)` plus
`sparse(param, rows, row_grads)` for barrierless per-grad updates
(RunAsyncLoop semantics: no barriers, latest-write-wins).
"""

from __future__ import annotations

from typing import Dict

import numpy as np


class ServerOptimizer:
    """Base: subclasses update in place (param is the server's array)."""

    def __init__(self, lr: float, attrs: Dict):
        self.lr = float(lr)
        self.attrs = attrs or {}
        self._acc: Dict[str, np.ndarray] = {}

    def _accum(self, key, like, fill=0.0):
        if key not in self._acc:
            self._acc[key] = np.full_like(like, fill)
        return self._acc[key]

    def dense(self, param: np.ndarray, grad: np.ndarray) -> None:
        raise NotImplementedError

    def sparse(self, param: np.ndarray, rows: np.ndarray,
               row_grads: np.ndarray) -> None:
        """Default row-wise path: gather, dense-update the slice, scatter.
        Duplicated rows must be pre-combined by the client (reference
        merge_ids semantics)."""
        sub = param[rows]
        self._sparse_rows(param, rows, sub, row_grads)

    def _sparse_rows(self, param, rows, sub, row_grads):
        raise NotImplementedError

    def state(self):
        return {"lr": self.lr, "attrs": self.attrs, "acc": self._acc}

    def load_state(self, st):
        self.lr = st["lr"]
        self.attrs = st["attrs"]
        self._acc = st["acc"]


class SGD(ServerOptimizer):
    def dense(self, param, grad):
        param -= self.lr * grad

    def _sparse_rows(self, param, rows, sub, row_grads):
        param[rows] = sub - self.lr * row_grads


class Momentum(ServerOptimizer):
    def dense(self, param, grad):
        mu = self.attrs.get("mu", 0.9)
        v = self._accum("velocity", param)
        v *= mu
        v += grad
        if self.attrs.get("use_nesterov"):
            param -= self.lr * (grad + mu * v)
        else:
            param -= self.lr * v

    def _sparse_rows(self, param, rows, sub, row_grads):
        mu = self.attrs.get("mu", 0.9)
        v = self._accum("velocity", param)
        vr = mu * v[rows] + row_grads
        v[rows] = vr
        if self.attrs.get("use_nesterov"):  # match the dense path exactly
            param[rows] = sub - self.lr * (row_grads + mu * vr)
        else:
            param[rows] = sub - self.lr * vr


class Adagrad(ServerOptimizer):
    def dense(self, param, grad):
        eps = self.attrs.get("epsilon", 1e-6)
        m = self._accum("moment", param)
        m += grad * grad
        param -= self.lr * grad / (np.sqrt(m) + eps)

    def _sparse_rows(self, param, rows, sub, row_grads):
        eps = self.attrs.get("epsilon", 1e-6)
        m = self._accum("moment", param)
        mr = m[rows] + row_grads * row_grads
        m[rows] = mr
        param[rows] = sub - self.lr * row_grads / (np.sqrt(mr) + eps)


class Adam(ServerOptimizer):
    def dense(self, param, grad):
        b1 = self.attrs.get("beta1", 0.9)
        b2 = self.attrs.get("beta2", 0.999)
        eps = self.attrs.get("epsilon", 1e-8)
        m = self._accum("moment1", param)
        v = self._accum("moment2", param)
        t = self._acc.setdefault("t", np.zeros((), np.int64))
        self._acc["t"] = t = t + 1
        m *= b1
        m += (1 - b1) * grad
        v *= b2
        v += (1 - b2) * grad * grad
        mhat = m / (1 - b1 ** int(t))
        vhat = v / (1 - b2 ** int(t))
        param -= self.lr * mhat / (np.sqrt(vhat) + eps)

    def _sparse_rows(self, param, rows, sub, row_grads):
        # per-row lazy adam (reference adam_op.h sparse path updates only
        # touched rows; a per-row step counter keeps bias correction local)
        b1 = self.attrs.get("beta1", 0.9)
        b2 = self.attrs.get("beta2", 0.999)
        eps = self.attrs.get("epsilon", 1e-8)
        m = self._accum("moment1", param)
        v = self._accum("moment2", param)
        steps = self._acc.setdefault(
            "row_t", np.zeros((param.shape[0],), np.int64))
        steps[rows] += 1
        t = steps[rows][:, None].astype(param.dtype)
        mr = b1 * m[rows] + (1 - b1) * row_grads
        vr = b2 * v[rows] + (1 - b2) * row_grads * row_grads
        m[rows] = mr
        v[rows] = vr
        mhat = mr / (1 - b1 ** t)
        vhat = vr / (1 - b2 ** t)
        param[rows] = sub - self.lr * mhat / (np.sqrt(vhat) + eps)


_KERNELS = {"sgd": SGD, "momentum": Momentum, "adagrad": Adagrad,
            "adam": Adam}


def make_optimizer(op_type: str, lr: float, attrs: Dict) -> ServerOptimizer:
    if op_type not in _KERNELS:
        raise NotImplementedError(
            f"server-side optimizer {op_type!r} not implemented; supported: "
            f"{sorted(_KERNELS)} (reference pserver optimize blocks support "
            f"any op — add the kernel here)")
    return _KERNELS[op_type](lr, attrs)
