"""WeightedAverage (reference: python/paddle/fluid/average.py)."""

from __future__ import annotations

import numpy as np

__all__ = ["WeightedAverage"]


def _is_number_or_matrix(var):
    return isinstance(var, (int, float, complex, np.ndarray)) or \
        np.isscalar(var)


class WeightedAverage:
    """Running weighted average of scalar batch statistics (reference
    average.py:30)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            value = np.asarray(value)
        if not np.isscalar(weight):
            weight = float(np.asarray(weight).reshape(-1)[0])
        value = float(np.asarray(value).reshape(-1)[0]) \
            if not np.isscalar(value) else float(value)
        if self.numerator is None:
            self.numerator, self.denominator = 0.0, 0.0
        self.numerator += value * weight
        self.denominator += weight

    def eval(self):
        if self.numerator is None or self.denominator == 0:
            raise ValueError(
                "WeightedAverage: there is no data to be averaged")
        return self.numerator / self.denominator
