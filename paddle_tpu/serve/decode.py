"""fluid-decode: the autoregressive serving engine.

`fluid-serve` (one-shot) pads a request, runs ONE prepared step, and
de-muxes rows. A generative request instead runs one PREFILL step plus
up to max_new_tokens DECODE steps, and the work outstanding per request
is unknown at admission — the two facts that make one-shot batching
useless for decode. The engine splits the phases:

- **Prefill** rides the ordinary bucket ladder: admitted prompts are
  grouped by their padded-length rung, batched up to the rows rung, and
  run through the prefill program (causal attention + paged KV cache
  write in one jitted step). The first generated token comes out of
  prefill's last-position logits — that moment is TTFT.
- **Decode** is a fixed-slot prepared step: every iteration runs ONE
  step of shape [max_slots] regardless of how many slots are live
  (inactive slots are masked lanes pointing at the trash block), so the
  step compiles exactly once and the compile cache stays warm across any
  request mix.
- **Continuous batching** (serve/batcher.py SlotScheduler): a finished
  sequence vacates its slot between steps and a queued request is
  prefilled into the hole while the other slots keep decoding — the
  batch never drains. `admission="drain"` keeps the classic
  drain-and-refill behavior for the bench A/B.

Sampling is greedy argmax on the host — generations are deterministic,
so continuous-vs-solo token parity is testable (and the loadgen's
wrong-token gate is exact). KV capacity is reserved worst-case at
admission (serve/kvcache.py): a running sequence can never strand, and
`CacheExhaustedError` is retriable backpressure at the door, foreshadowed
by the `kv_cache_exhaustion` health detector.

Hot swap: sequences in flight finish on the version they started on (the
engine holds a registry refcount while any slot is live); when a new
version is published the engine stops admitting, drains, releases, and
rebinds — the swap costs one batch drain, never a wrong-version token.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FuturesTimeout
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import flags as _flags
from ..observe import metrics as _metrics
from ..observe import xray as _xray
from .batcher import SlotScheduler
from .errors import (BadRequestError, CacheExhaustedError,
                     DeadlineExceededError, ModelUnavailableError,
                     QueueFullError, ServeError)

_STREAM_END = object()


class GenerationResult:
    """What a finished generation resolves to."""

    __slots__ = ("tokens", "prompt_len", "finish_reason", "ttft_us",
                 "version_id", "kv")

    def __init__(self, tokens, prompt_len, finish_reason, ttft_us,
                 version_id, kv=None):
        self.tokens = tokens              # generated tokens (no prompt)
        self.prompt_len = prompt_len
        self.finish_reason = finish_reason  # "eos" | "length" | "prefill"
        self.ttft_us = ttft_us
        self.version_id = version_id
        # prefill_only submits resolve with the prompt's extracted KV
        # blocks here (fluid-torrent streams them to a decode replica)
        self.kv = kv

    def __repr__(self):
        return (f"GenerationResult({len(self.tokens)} tokens, "
                f"{self.finish_reason!r}, ttft {self.ttft_us:.0f}us)")


class GenerationStream:
    """submit_stream handle: iterate tokens as they are produced; the
    future resolves to the full GenerationResult (or the error)."""

    def __init__(self, future: Future):
        self.future = future
        self._q: "queue.Queue" = queue.Queue()

    def _push(self, tok):
        self._q.put(tok)

    def _finish(self):
        self._q.put(_STREAM_END)

    def __iter__(self):
        while True:
            t = self._q.get()
            if t is _STREAM_END:
                return
            yield t


class _GenRequest:
    __slots__ = ("prompt", "max_new", "future", "stream", "deadline",
                 "t_enq", "ctx", "ts_wall", "resolved", "prefill_only",
                 "premat", "first_token")

    def __init__(self, prompt, max_new, future, stream, deadline, ctx,
                 ts_wall, prefill_only=False, premat=None,
                 first_token=None):
        self.prompt = prompt
        self.max_new = max_new
        self.future = future
        self.stream = stream
        self.deadline = deadline          # absolute monotonic s or None
        self.t_enq = time.monotonic()
        self.ctx = ctx
        self.ts_wall = ts_wall
        self.resolved = False             # guarded by the engine cond
        # fluid-torrent disaggregation: prefill_only stops after the
        # first token and resolves with the extracted KV payload; premat
        # is the inverse — a KV payload prefilled elsewhere, injected at
        # admission with `first_token` seeding the first decode step
        self.prefill_only = prefill_only
        self.premat = premat
        self.first_token = first_token


class _Slot:
    """Slot state. Occupies its scheduler slot from ADMISSION (so slot
    accounting is correct while its prefill is still running on the
    engine thread); `started` flips once prefill produced the first
    token and decode may include the slot."""

    __slots__ = ("req", "ctx_len", "last_token", "generated", "ttft_us",
                 "started")

    def __init__(self, req):
        self.req = req
        self.ctx_len = 0                  # tokens whose K/V are in cache
        self.last_token = -1              # next decode step's input
        self.generated: List[int] = []
        self.ttft_us = 0.0
        self.started = False


class DecodeEngine:
    """One generative model's slots + decode thread."""

    def __init__(self, registry, name: str, max_queue: int = 256,
                 admission: str = "continuous",
                 simulate_prefill_us_per_token: float = 0.0,
                 simulate_decode_step_us: float = 0.0):
        self._registry = registry
        self._name = name
        # rehearsal-rig knobs (tools/bench honesty posture): model the
        # compute-bound prefill (us per PADDED token of the chunk) and
        # memory-bound decode (us per fixed-slot STEP — the whole-cache
        # read every step pays regardless of live lanes) so topology
        # effects show on the CPU test backend
        self._sim_prefill_us = float(simulate_prefill_us_per_token)
        self._sim_decode_us = float(simulate_decode_step_us)
        self._requant_seen = 0            # engine thread only
        sig = registry.get(name).decode.signature
        self._sched = SlotScheduler(sig["max_slots"], max_queue=max_queue,
                                    admission=admission)
        self._cond = self._sched.cond
        self._ver = None                  # acquired while slots are live
        self._closed = False
        self._m_requests = _metrics.counter(
            "serve_generate_requests_total",
            "generative requests by outcome")
        self._m_tokens = _metrics.counter(
            "serve_decode_tokens_total", "tokens generated, per model")
        self._m_ttft = _metrics.histogram(
            "serve_ttft_us", "submit -> first token per generation")
        self._m_steps = _metrics.counter(
            "serve_decode_steps_total", "fixed-slot decode steps run")
        self._m_occupancy = _metrics.histogram(
            "serve_decode_occupancy", "live slots per decode step")
        self._m_step_latency = _metrics.histogram(
            "serve_decode_step_us", "decode step wall time")
        self._m_prefill_latency = _metrics.histogram(
            "serve_prefill_us", "prefill step wall time")
        self._m_requant = _metrics.counter(
            "serve_kv_requant_events_total",
            "int8 KV whole-block requantize events, per model")
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"serve-decode-{name}")
        self._thread.start()

    # -- producer side ----------------------------------------------------

    def submit(self, prompt: Sequence[int],
               max_new_tokens: int = 16,
               deadline_ms: Optional[float] = None,
               stream: bool = False, prefill_only: bool = False):
        """Enqueue one generation. Returns its Future (stream=False) or a
        GenerationStream (stream=True). Rejections are immediate:
        QueueFullError / CacheExhaustedError are retriable backpressure,
        BadRequestError means the prompt can never run.

        `prefill_only=True` is fluid-torrent's prefill half: run the
        prompt's prefill step, resolve the Future with a
        GenerationResult carrying the first token AND the prompt's
        extracted KV payload (`result.kv`), and vacate immediately — the
        generation continues on whichever replica `submit_prefilled`
        injects the payload into."""
        ver = self._registry.get(self._name)
        if ver.decode is None:
            raise BadRequestError(
                f"model {self._name!r} has no decode program — "
                f"a one-shot model cannot generate")
        sig = ver.decode.signature
        if prefill_only and stream:
            raise BadRequestError(
                "prefill_only produces one token — streaming does not "
                "apply")
        prompt = [int(t) for t in prompt]
        self._validate_prompt(prompt, sig)
        max_new = int(max_new_tokens)
        if not prefill_only:
            if max_new < 1:
                raise BadRequestError("max_new_tokens must be >= 1")
            if len(prompt) + max_new > sig["max_context"]:
                raise BadRequestError(
                    f"prompt {len(prompt)} + max_new_tokens {max_new} "
                    f"exceeds max_context {sig['max_context']}")
        ctx = _xray.child_of() if _flags.get_flag("observe") else None
        ts_wall = time.time() if ctx is not None else 0.0
        fut: Future = Future()
        gstream = GenerationStream(fut) if stream else None
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        req = _GenRequest(prompt, max_new, fut, gstream, deadline, ctx,
                          ts_wall, prefill_only=prefill_only)
        self._enqueue(req)
        return gstream if stream else fut

    def submit_prefilled(self, prompt: Sequence[int], first_token: int,
                         kv: dict, max_new_tokens: int = 16,
                         deadline_ms: Optional[float] = None,
                         stream: bool = False):
        """Admit a generation whose prefill ran ELSEWHERE (fluid-torrent
        disaggregation): `kv` is the payload a `prefill_only` submit
        resolved with — the prompt's cache-block rows (plus int8
        per-block scales when the residency is quantized). The engine
        copies those rows into this replica's cache arrays at its own
        block ids and enters decode directly; `first_token` (the remote
        prefill's argmax) counts as generated token #1 exactly like the
        local prefill path, so `max_new_tokens` means the same thing in
        both modes."""
        ver = self._registry.get(self._name)
        if ver.decode is None:
            raise BadRequestError(
                f"model {self._name!r} has no decode program — "
                f"a one-shot model cannot generate")
        sig = ver.decode.signature
        prompt = [int(t) for t in prompt]
        self._validate_prompt(prompt, sig)
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise BadRequestError("max_new_tokens must be >= 1")
        if len(prompt) + max_new > sig["max_context"]:
            raise BadRequestError(
                f"prompt {len(prompt)} + max_new_tokens {max_new} "
                f"exceeds max_context {sig['max_context']}")
        first_token = int(first_token)
        if first_token < 0 or first_token >= sig["vocab"]:
            raise BadRequestError(
                f"first_token out of range for vocab {sig['vocab']}")
        if not isinstance(kv, dict) or not isinstance(kv.get("cache"),
                                                      dict):
            raise BadRequestError(
                "kv payload must be a dict with a 'cache' mapping "
                "(cache var -> [n_blocks, ...] rows)")
        if str(kv.get("kv_dtype", "fp32")) != \
                str(sig.get("kv_dtype", "fp32")):
            raise BadRequestError(
                f"kv payload residency {kv.get('kv_dtype')!r} does not "
                f"match this model's {sig.get('kv_dtype', 'fp32')!r}")
        need = -(-len(prompt) // sig["block_size"])
        for cname in sig["cache_vars"]:
            rows = kv["cache"].get(cname)
            if rows is None or len(rows) < need:
                raise BadRequestError(
                    f"kv payload is missing block rows for {cname!r} "
                    f"({need} needed)")
        if sig.get("scale_vars") and not isinstance(kv.get("scales"),
                                                    dict):
            raise BadRequestError(
                "int8 kv payload must carry per-block 'scales'")
        ctx = _xray.child_of() if _flags.get_flag("observe") else None
        ts_wall = time.time() if ctx is not None else 0.0
        fut: Future = Future()
        gstream = GenerationStream(fut) if stream else None
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        req = _GenRequest(prompt, max_new, fut, gstream, deadline, ctx,
                          ts_wall, premat=kv, first_token=first_token)
        self._enqueue(req)
        return gstream if stream else fut

    def _validate_prompt(self, prompt, sig):
        if not prompt:
            raise BadRequestError("empty prompt")
        if any(t < 0 or t >= sig["vocab"] for t in prompt):
            raise BadRequestError(
                f"prompt token out of range for vocab {sig['vocab']}")
        max_rung = max(sig["prefill_seq_rungs"])
        if len(prompt) > max_rung:
            raise BadRequestError(
                f"prompt of {len(prompt)} tokens exceeds the largest "
                f"prefill rung {max_rung}")

    def _enqueue(self, req: _GenRequest):
        with self._cond:
            if self._closed:
                raise ModelUnavailableError(
                    f"model {self._name!r}: decode engine is shut down")
            try:
                self._sched.submit_locked(req)
            except QueueFullError:
                self._m_requests.inc(model=self._name,
                                     outcome="queue_full")
                raise QueueFullError(
                    f"model {self._name!r}: "
                    f"{len(self._sched.pending)} generations queued "
                    f"(max_queue={self._sched.max_queue}) — retry with "
                    f"backoff") from None

    def generate(self, prompt, max_new_tokens: int = 16,
                 deadline_ms: Optional[float] = None) -> GenerationResult:
        fut = self.submit(prompt, max_new_tokens=max_new_tokens,
                          deadline_ms=deadline_ms)
        if deadline_ms is None:
            return fut.result()
        # _FuturesTimeout: on Python < 3.11 concurrent.futures raises its
        # OWN TimeoutError class, not the builtin (same note as
        # InferenceServer.infer)
        try:
            return fut.result(timeout=deadline_ms / 1e3 + 30.0)
        except (TimeoutError, _FuturesTimeout):
            raise DeadlineExceededError(
                f"model {self._name!r}: no generation result within "
                f"deadline {deadline_ms} ms (+30 s slack)") from None

    def stats(self) -> dict:
        with self._cond:
            active = self._sched.active_count()
            pending = len(self._sched.pending)
        kv = None
        try:
            dec = self._registry.get(self._name).decode
            if dec is not None:
                kv = {"blocks_in_use": dec.kvcache.in_use(),
                      "blocks_capacity": dec.kvcache.capacity}
        except ServeError:
            pass
        ttft = self._m_ttft.summary(model=self._name)
        return {
            "active_slots": active,
            "queued": pending,
            "admission": self._sched.admission,
            "tokens": self._m_tokens.value(model=self._name),
            "steps": self._m_steps.value(model=self._name),
            "avg_ttft_us": round(ttft["mean"], 1) if ttft else 0.0,
            "kv": kv,
        }

    # -- lifecycle spans / outcomes ---------------------------------------

    def _finish_req(self, req: _GenRequest, outcome: str, result=None,
                    exc=None):
        # exactly-once: close() (caller thread) can race the engine
        # thread finishing the same request — the loser must not touch
        # the already-resolved Future (set_running_or_notify_cancel on a
        # FINISHED future raises out of the caller's shutdown path)
        with self._cond:
            if req.resolved:
                return
            req.resolved = True
        self._m_requests.inc(model=self._name, outcome=outcome)
        if req.ctx is not None:
            _xray.record_span(
                "serve_generate", req.ctx, req.ts_wall,
                time.monotonic() - req.t_enq, cat="serve",
                model=self._name, outcome=outcome,
                prompt_len=len(req.prompt),
                tokens=len(result.tokens) if result is not None else 0)
        if req.stream is not None:
            req.stream._finish()
        if req.future.set_running_or_notify_cancel():
            if exc is not None:
                req.future.set_exception(exc)
            else:
                req.future.set_result(result)

    # -- engine loop ------------------------------------------------------

    def _loop(self):
        while True:
            with self._cond:
                while not self._closed and not self._sched.pending \
                        and self._sched.active_count() == 0:
                    # going idle releases the version pin so a swapped-out
                    # version can fully retire while no work is in flight
                    if self._ver is not None:
                        self._release_version()
                    self._cond.wait()
                if self._closed:
                    return
                now = time.monotonic()
                expired = self._sched.expire_locked(
                    lambda r: r.deadline is not None and r.deadline <= now)
            for r in expired:
                self._finish_req(r, "deadline", exc=DeadlineExceededError(
                    f"model {self._name!r}: generation deadline expired "
                    f"after {(time.monotonic() - r.t_enq) * 1e3:.1f} ms "
                    f"in queue"))
            try:
                self._rebind_if_needed()
                self._admit_and_prefill()
                self._decode_step()
                if self._ver is None:
                    # pending work but no servable version (initial load
                    # failed / registry closing): don't hot-spin — wake
                    # on the next submit/close or re-check shortly
                    with self._cond:
                        if not self._closed:
                            self._cond.wait(0.05)
            except Exception as e:          # noqa: BLE001
                # a broken step must fail the sequences riding it, not
                # kill the engine thread — and a PERSISTENT error (e.g.
                # a registry mid-teardown) must not become a hot
                # exception loop
                self._fail_all(e)
                with self._cond:
                    if not self._closed:
                        self._cond.wait(0.05)

    def _release_version(self):
        self._registry.release(self._ver)
        self._ver = None

    def _rebind_if_needed(self):
        """Bind the current published version when unbound; when a NEW
        version was published, stop admitting and let active sequences
        drain on the old one, then flip."""
        try:
            cur = self._registry.get(self._name)
        except ServeError:
            return
        if self._ver is None:
            self._ver = self._registry.acquire(self._name)
            self._requant_seen = 0        # fresh version, fresh counter
            with self._cond:
                if self._sched.n_slots != \
                        self._ver.decode.signature["max_slots"]:
                    self._sched.resize_locked(
                        self._ver.decode.signature["max_slots"])
            return
        if cur.version_id != self._ver.version_id:
            with self._cond:
                active = self._sched.active_count()
            if active == 0:
                self._release_version()
                self._rebind_if_needed()

    def _swap_pending(self) -> bool:
        """True while a newer version is published than the one bound —
        admission pauses so the bound version can drain."""
        if self._ver is None:
            return False
        try:
            return self._registry.get(self._name).version_id \
                != self._ver.version_id
        except ServeError:
            return False

    # -- admission + prefill ----------------------------------------------

    def _admit_and_prefill(self):
        if self._ver is None or self._swap_pending():
            return
        dec = self._ver.decode
        sig = dec.signature
        admitted: List = []               # (slot, _Slot)
        rejected = None
        with self._cond:
            for slot in self._sched.admissible_locked():
                if not self._sched.pending:
                    break
                req = self._sched.pending[0]
                # prefill_only never decodes: reserve just the prompt
                total = len(req.prompt) + \
                    (0 if req.prefill_only else req.max_new)
                try:
                    dec.kvcache.reserve(slot, total)
                except CacheExhaustedError as e:
                    if self._sched.active_count() == 0 and not admitted:
                        # nothing running will ever free blocks: this
                        # request can never be admitted — reject it
                        self._sched.pending.popleft()
                        rejected = (req, e)
                    break                 # backpressure: wait for frees
                self._sched.pending.popleft()
                state = _Slot(req)
                self._sched.occupy_locked(slot, state)
                admitted.append((slot, state))
        if rejected is not None:
            self._finish_req(rejected[0], "cache_exhausted",
                             exc=rejected[1])
        if not admitted:
            return
        # injected (premat) admissions skip prefill entirely: copy the
        # wire-delivered KV rows into the cache and go straight to decode
        fresh = []
        for slot, state in admitted:
            if state.req.premat is not None:
                self._inject_premat(dec, sig, slot, state)
            else:
                fresh.append((slot, state))
        if not fresh:
            return
        # group by prompt-length rung; each group is one prefill step
        ladder = self._ver.ladder
        groups: Dict[int, List] = {}
        for slot, state in fresh:
            rung = ladder.dim_rung("tokens", 1, len(state.req.prompt))
            groups.setdefault(rung, []).append((slot, state))
        for rung, members in groups.items():
            max_rows = ladder.max_rows
            for i in range(0, len(members), max_rows):
                self._prefill_chunk(dec, sig, rung, members[i:i + max_rows])

    def _prefill_chunk(self, dec, sig, rung: int, members: List):
        rows = self._ver.ladder.rows_rung(len(members))
        tokens = np.zeros((rows, rung), np.int64)
        seq_lens = np.zeros((rows,), np.int32)
        bt = np.zeros((rows, sig["max_blocks_per_seq"]), np.int32)
        for r, (slot, state) in enumerate(members):
            prompt = state.req.prompt
            tokens[r, :len(prompt)] = prompt
            seq_lens[r] = len(prompt)
            tables = dec.kvcache.ensure(slot, len(prompt))
            bt[r] = tables[slot]
        t0 = time.perf_counter()
        logits, = self._ver.prepared.run({
            "tokens": tokens, "block_tables": bt, "seq_lens": seq_lens})
        if self._sim_prefill_us > 0.0:
            # compute-bound phase: cost scales with the chunk's padded
            # token area (the engine thread IS the chip analog, so this
            # stall delays everything behind it — the interference the
            # torrent bench measures)
            time.sleep(self._sim_prefill_us * rows * rung / 1e6)
        self._m_prefill_latency.observe(
            (time.perf_counter() - t0) * 1e6, model=self._name)
        # a warm=False generative version becomes "warmed" by serving
        # (same /readyz contract as the MicroBatcher one-shot path —
        # without this, a cold-loaded generative server reports unready
        # forever while generating fine)
        self._ver.warmed = True
        done = time.monotonic()
        for r, (slot, state) in enumerate(members):
            tok = int(np.argmax(logits[r]))
            state.ttft_us = (done - state.req.t_enq) * 1e6
            self._m_ttft.observe(state.ttft_us, model=self._name)
            self._m_tokens.inc(model=self._name)
            if state.req.prefill_only:
                # fluid-torrent prefill half: hand the prompt's KV rows
                # (still allocated this instant) to the caller, then
                # vacate — a decode replica owns the rest
                kv = self._extract_kv(dec, sig, slot,
                                      len(state.req.prompt))
                self._vacate(slot)
                self._finish_req(state.req, "ok",
                                 result=GenerationResult(
                                     [tok], len(state.req.prompt),
                                     "prefill", state.ttft_us,
                                     self._ver.version_id, kv=kv))
                continue
            state.ctx_len = len(state.req.prompt)
            state.last_token = tok
            state.generated = [tok]
            state.started = True
            if state.req.stream is not None:
                state.req.stream._push(tok)
            self._maybe_finish(slot, state, tok, sig)

    # -- fluid-torrent KV extraction / injection ---------------------------

    def _extract_kv(self, dec, sig, slot: int, prompt_len: int) -> dict:
        """Copy the slot's resident KV block rows (plus int8 per-block
        scales) out of the bound version's scope. Rows are position-
        ordered, so they can be written at ANY replica's block ids — the
        block table is the only indirection. Runs on the engine thread
        between steps, so the arrays are quiescent."""
        ids = dec.kvcache.slot_blocks(slot)
        scope = self._ver.scope
        cache = {}
        for cname in sig["cache_vars"]:
            arr = np.asarray(scope.find_var(cname))
            cache[cname] = np.array(arr[ids])
        out = {"cache": cache, "prompt_len": int(prompt_len),
               "n_blocks": len(ids),
               "kv_dtype": str(sig.get("kv_dtype", "fp32"))}
        smap = sig.get("scale_vars") or {}
        if smap:
            out["scales"] = {
                c: np.array(np.asarray(scope.find_var(s))[ids])
                for c, s in smap.items()}
        return out

    def _inject_premat(self, dec, sig, slot: int, state: _Slot):
        """Write a wire-delivered KV payload into this replica's cache
        at the slot's freshly allocated block ids and seed decode state
        — the injected sequence's next step is an ordinary decode append
        at position prompt_len. Engine thread only (scope.set_var bumps
        the version so the next step re-gathers; no recompile)."""
        req = state.req
        n = len(req.prompt)
        dec.kvcache.ensure(slot, n)
        ids = dec.kvcache.slot_blocks(slot)
        scope = self._ver.scope
        smap = sig.get("scale_vars") or {}
        scales = req.premat.get("scales") or {}
        for cname in sig["cache_vars"]:
            base = np.array(np.asarray(scope.find_var(cname)))
            rows = np.asarray(req.premat["cache"][cname])
            base[ids] = rows[:len(ids)].astype(base.dtype)
            scope.set_var(cname, base)
            sname = smap.get(cname)
            if sname is not None and cname in scales:
                sb = np.array(np.asarray(scope.find_var(sname)))
                sb[ids] = np.asarray(scales[cname],
                                     np.float32)[:len(ids)]
                scope.set_var(sname, sb)
        tok = int(req.first_token)
        # local TTFT covers admit+copy only; the end-to-end (wire
        # included) TTFT is metered at the torrent layer
        state.ttft_us = (time.monotonic() - req.t_enq) * 1e6
        self._m_ttft.observe(state.ttft_us, model=self._name)
        state.ctx_len = n
        state.last_token = tok
        state.generated = [tok]
        state.started = True
        if req.stream is not None:
            req.stream._push(tok)
        self._maybe_finish(slot, state, tok, sig)

    def _sample_requant(self, sig):
        """Meter int8 whole-block requantize events: the jitted decode
        step increments the [1] int32 requant var; the engine publishes
        the delta. Engine thread only."""
        rq = sig.get("requant_var")
        if rq is None:
            return
        try:
            val = int(np.asarray(self._ver.scope.find_var(rq))[0])
        except Exception:                 # noqa: BLE001
            return
        if val > self._requant_seen:
            self._m_requant.inc(val - self._requant_seen,
                                model=self._name)
        self._requant_seen = val

    # -- decode ------------------------------------------------------------

    def _decode_step(self):
        if self._ver is None:
            return
        dec = self._ver.decode
        sig = dec.signature
        with self._cond:
            live = [(i, s) for i, s in enumerate(self._sched.slots)
                    if s is not None and s.started]
        if not live:
            return
        S = self._sched.n_slots
        tokens = np.zeros((S, 1), np.int64)
        seq_lens = np.zeros((S,), np.int32)
        for i, s in live:
            dec.kvcache.ensure(i, s.ctx_len + 1)
            tokens[i, 0] = s.last_token
            seq_lens[i] = s.ctx_len + 1
        t0 = time.perf_counter()
        logits, = dec.prepared.run({
            "tokens": tokens,
            "block_tables": dec.kvcache.block_tables,
            "seq_lens": seq_lens})
        if self._sim_decode_us > 0.0:
            # memory-bound phase: a fixed-slot step pays (roughly) the
            # whole-cache read however many lanes are live — per-STEP
            # cost, which is the batching dividend disaggregation keeps
            time.sleep(self._sim_decode_us / 1e6)
        self._m_step_latency.observe(
            (time.perf_counter() - t0) * 1e6, model=self._name)
        self._m_steps.inc(model=self._name)
        self._m_occupancy.observe(len(live), model=self._name)
        self._sample_requant(sig)
        now = time.monotonic()
        for i, s in live:
            s.ctx_len += 1
            tok = int(np.argmax(logits[i]))
            s.generated.append(tok)
            s.last_token = tok
            self._m_tokens.inc(model=self._name)
            if s.req.stream is not None:
                s.req.stream._push(tok)
            if self._maybe_finish(i, s, tok, sig):
                continue
            if s.req.deadline is not None and now >= s.req.deadline:
                # mid-decode deadline (a COMPLETED generation above wins
                # over a simultaneous expiry): stop burning slot-steps on
                # a caller who has given up; streamed tokens were
                # delivered
                self._vacate(i)
                self._finish_req(s.req, "deadline",
                                 exc=DeadlineExceededError(
                                     f"model {self._name!r}: generation "
                                     f"deadline expired after "
                                     f"{len(s.generated)} tokens"))

    def _maybe_finish(self, slot: int, s: _Slot, tok: int, sig) -> bool:
        eos = sig.get("eos_token")
        reason = None
        if eos is not None and tok == int(eos):
            reason = "eos"
        elif len(s.generated) >= s.req.max_new:
            reason = "length"
        if reason is None:
            return False
        self._vacate(slot)
        self._finish_req(s.req, "ok", result=GenerationResult(
            list(s.generated), len(s.req.prompt), reason, s.ttft_us,
            self._ver.version_id))
        return True

    def _vacate(self, slot: int):
        self._ver.decode.kvcache.free_slot(slot)
        with self._cond:
            self._sched.vacate_locked(slot)

    def _fail_all(self, exc: Exception):
        with self._cond:
            live = [(i, s) for i, s in enumerate(self._sched.slots)
                    if s is not None]
        for i, s in live:
            self._vacate(i)
            self._finish_req(s.req, "error", exc=exc)

    def close(self):
        with self._cond:
            if self._closed:
                return
            self._closed = True
            dead = list(self._sched.pending)
            self._sched.pending.clear()
            live = [(i, s) for i, s in enumerate(self._sched.slots)
                    if s is not None]
            for i, _ in live:
                self._sched.slots[i] = None
            self._cond.notify_all()
        exc = ModelUnavailableError(
            f"model {self._name!r}: decode engine shut down with the "
            f"generation in flight")
        for r in dead:
            self._finish_req(r, "error", exc=exc)
        for _, s in live:
            self._finish_req(s.req, "error", exc=exc)
        # join BEFORE dropping the version pin: the loop may be mid-step
        # on the bound version's prepared handle
        self._thread.join(timeout=10)
        if self._ver is not None and self._ver.decode is not None:
            # return the killed sequences' blocks (after the join — the
            # mid-step loop must not see its tables freed under it): the
            # version may keep serving (kind flip re-registration), and
            # stranded blocks would both leak capacity and freeze the
            # occupancy gauge
            for i, _ in live:
                self._ver.decode.kvcache.free_slot(i)
        if self._ver is not None:
            self._release_version()
