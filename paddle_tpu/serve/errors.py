"""Serving error taxonomy.

Every error a request can hit carries a `retriable` class attribute so
clients (and the load generator) can tell backpressure from bugs without
string matching:

- retriable=True  — transient serving-side condition: the queue was full
  (admission control fast-reject), the deadline expired while queued, or
  the model was mid-(re)load. Retry with backoff.
- retriable=False — the request or deployment is wrong: unknown model,
  malformed feed, corrupt model dir. Retrying cannot help.
"""

from __future__ import annotations


class ServeError(RuntimeError):
    """Base of every serving-path error."""

    retriable = False


class ModelNotFoundError(ServeError):
    """No model registered under the requested name."""


class ModelUnavailableError(ServeError):
    """The model exists but has no servable version right now (initial
    load in flight, or the registry is shutting down)."""

    retriable = True


class BadRequestError(ServeError):
    """The feed doesn't fit the model: wrong feed names, disagreeing
    batch dims, a static-dim mismatch, or more rows than the ladder's
    largest bucket."""


class QueueFullError(ServeError):
    """Admission control fast-reject: the model's request queue is at
    capacity. The request was NOT enqueued; retry with backoff."""

    retriable = True


class DeadlineExceededError(ServeError):
    """The request's deadline expired before its batch ran. The request
    was dropped without executing."""

    retriable = True


class KVTransferError(ServeError):
    """fluid-torrent: a wire-streamed KV transfer could not complete —
    the receiving decode replica is gone, lost its staging state, or the
    transfer was superseded by a newer attempt. The generation itself is
    intact on the client's side of the contract: re-prefill on any
    replica (greedy decoding is deterministic, so a re-prefill reproduces
    the same tokens) and stream again."""

    retriable = True


class CacheExhaustedError(ServeError):
    """fluid-decode admission control: the paged KV cache cannot reserve
    enough blocks to guarantee the generation completes. The request was
    NOT admitted; blocks free as running sequences finish — retry with
    backoff (the `kv_cache_exhaustion` health detector fires before this
    starts happening)."""

    retriable = True
