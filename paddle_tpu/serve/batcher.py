"""Dynamic micro-batcher: coalesce concurrent requests into TPU batches.

A TPU step has near-constant host+dispatch cost whether it computes 1
row or 16, so serving throughput is won by running FEWER, FULLER steps —
the request-batching layer of the TensorFlow serving design, rebuilt on
the PreparedProgram fast path. Per (model, group-signature) queues hold
planned requests; a dedicated executor thread per model coalesces a
queue's requests up to the ladder's largest rung or until the oldest
request has waited `batch_timeout_ms`, pads the coalesced rows up to a
bucket rung, runs ONE prepared step, and de-multiplexes the output rows
back onto each caller's Future.

Admission control is a bounded queue with fast-reject: a request that
arrives when `max_queue` requests are already waiting fails immediately
with the retriable QueueFullError — callers get backpressure in
microseconds instead of a timeout later. Each request may carry a
deadline; a request whose deadline expires while queued is dropped with
DeadlineExceededError without ever occupying the chip.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

from .. import flags as _flags
from ..observe import metrics as _metrics
from ..observe import xray as _xray
from .bucketing import concat_requests, pad_rows, plan_request
from .errors import (BadRequestError, DeadlineExceededError,
                     ModelUnavailableError, QueueFullError, ServeError)

# observe-flag probe for submit(), memoized on the flag registry version
# (same idiom as xray._trace_on): submit runs once per request, and at
# serve rates the registry dict lookups are measurable in the horizon A/B
_observe_cache = (-1, False)


def _observe_on() -> bool:
    global _observe_cache
    ver = _flags.version()
    cached = _observe_cache
    if cached[0] != ver:
        cached = _observe_cache = (ver, bool(_flags.get_flag("observe")))
    return cached[1]


class _Request:
    __slots__ = ("planned", "future", "deadline", "t_enq", "ctx", "ts_wall")

    def __init__(self, planned, future, deadline, ctx=None, ts_wall=0.0):
        self.planned = planned
        self.future = future
        self.deadline = deadline        # absolute monotonic s, or None
        self.t_enq = time.monotonic()
        # fluid-xray (observe on): the request's span context, captured
        # on the SUBMITTING thread so the whole queue->batch->de-mux
        # lifecycle lands in the caller's trace even though it completes
        # on the executor thread
        self.ctx = ctx
        self.ts_wall = ts_wall


class SlotScheduler:
    """fluid-decode: fixed-slot admission for multi-step generative work.

    One-shot inference coalesces a QUEUE into a batch and the batch
    drains atomically; a generative batch never drains atomically —
    sequences finish at wildly different steps. The scheduler therefore
    tracks a fixed array of SLOTS (the decode step's batch rows): a
    finished sequence vacates its slot mid-batch and the next queued
    request is admitted into the hole without stopping the slots still
    running — CONTINUOUS batching. `admission="drain"` is the deliberate
    strawman (refill only when every slot is empty — the classic
    drain-and-refill baseline the bench A/Bs against).

    Admission control mirrors MicroBatcher: a bounded pending queue with
    fast-reject (QueueFullError) and queued-deadline expiry. The decode
    engine owns WHAT runs in a slot; the scheduler owns which slots run.
    """

    def __init__(self, n_slots: int, max_queue: int = 256,
                 admission: str = "continuous"):
        if admission not in ("continuous", "drain"):
            raise ValueError(
                f"admission must be 'continuous' or 'drain', "
                f"got {admission!r}")
        self.n_slots = int(n_slots)
        self.admission = admission
        self.max_queue = int(max_queue)
        self.cond = threading.Condition()
        self.slots: List[Optional[object]] = [None] * self.n_slots
        self.pending: deque = deque()

    # -- producer side (locked by callers via self.cond) ------------------

    def submit_locked(self, item) -> None:
        if len(self.pending) >= self.max_queue:
            raise QueueFullError(
                f"{len(self.pending)} generations already queued "
                f"(max_queue={self.max_queue}) — retry with backoff")
        self.pending.append(item)
        self.cond.notify_all()

    # -- engine side ------------------------------------------------------

    def active_count(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def expire_locked(self, predicate) -> List[object]:
        """Pop every pending item for which `predicate(item)` is true
        (queued-deadline sweep)."""
        dead = [r for r in self.pending if predicate(r)]
        if dead:
            self.pending = deque(r for r in self.pending
                                 if not predicate(r))
        return dead

    # continuous-admission hysteresis: at full occupancy roughly one slot
    # frees per decode step, and admitting it alone costs a whole
    # single-row prefill step per decode step — measured to HALVE decode
    # throughput at deep-queue saturation. Waiting for a 2-slot admission
    # batch amortizes the prefill without hurting the underutilized case
    # (when fewer requests than this are waiting, admission is immediate).
    ADMIT_BATCH = 2

    def admissible_locked(self) -> List[int]:
        """Free slot indices the policy allows filling right now."""
        free = [i for i, s in enumerate(self.slots) if s is None]
        if not self.pending or not free:
            return []
        if self.admission == "drain" and self.active_count():
            return []     # the strawman: wait for the whole batch
        want = min(self.ADMIT_BATCH, len(self.pending), self.n_slots)
        if self.admission == "continuous" and len(free) < want:
            return []     # let a small admission batch accumulate
        return free

    def occupy_locked(self, slot: int, state) -> None:
        assert self.slots[slot] is None
        self.slots[slot] = state

    def vacate_locked(self, slot: int) -> None:
        self.slots[slot] = None
        self.cond.notify_all()

    def resize_locked(self, n_slots: int) -> None:
        """Rebind-time resize (hot swap to a version with a different
        max_slots); only legal while every slot is vacant."""
        assert self.active_count() == 0
        self.n_slots = int(n_slots)
        self.slots = [None] * self.n_slots


class MicroBatcher:
    """One model's queues + executor thread."""

    def __init__(self, registry, name: str, batch_timeout_ms: float = 2.0,
                 max_queue: int = 256):
        self._registry = registry
        self._name = name
        self._timeout_s = max(batch_timeout_ms, 0.0) / 1e3  # guarded_by: self._cond
        self._max_queue = max_queue
        self._queues: Dict[Tuple, deque] = {}  # guarded_by: self._cond
        self._cond = threading.Condition()
        self._pending = 0  # guarded_by: self._cond
        self._closed = False  # guarded_by: self._cond
        self._m_requests = _metrics.counter(
            "serve_requests_total", "serving requests by outcome")
        self._m_rejects = _metrics.counter(
            "serve_rejects_total", "fast-rejected requests by reason")
        self._m_latency = _metrics.histogram(
            "serve_request_latency_us", "enqueue->result per request")
        self._m_batch_latency = _metrics.histogram(
            "serve_batch_latency_us", "prepared step wall per batch")
        self._m_occupancy = _metrics.histogram(
            "serve_batch_occupancy", "requests coalesced per batch")
        self._m_rows = _metrics.histogram(
            "serve_batch_rows", "real (unpadded) rows per batch")
        self._m_waste = _metrics.histogram(
            "serve_padding_waste_ratio",
            "padded-but-dead row fraction per batch")
        self._m_bucket = _metrics.counter(
            "serve_bucket_fills_total",
            "batches by bucket fit (exact = no row padding)")
        self._m_depth = _metrics.gauge(
            "serve_queue_depth", "requests waiting, per model")
        # fluid-pulse: the saturation detector needs depth AND capacity
        # from the registry to compute depth/capacity per model
        self._m_qcap = _metrics.gauge(
            "serve_queue_capacity", "admission-control bound, per model")
        self._m_qcap.set(self._max_queue, model=name)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name=f"serve-exec-{name}")
        self._thread.start()

    # -- producer side ---------------------------------------------------

    def submit(self, feed, deadline_ms: Optional[float] = None) -> Future:
        """Plan, admit and enqueue one request; returns its Future."""
        ctx = _xray.child_of() if _observe_on() else None
        ts_wall = time.time() if ctx is not None else 0.0
        t_sub = time.monotonic()
        # cheap pre-check BEFORE planning: under overload the fast-reject
        # must not pay plan_request's pad/cast array copies per bounced
        # request (the authoritative check re-runs under the lock below)
        if self._pending >= self._max_queue:  # race_lint: ignore[unguarded-read] — benign racy fast-path; authoritative re-check under the lock below
            self._reject_span(ctx, ts_wall, t_sub, "queue_full")
            self._reject_full()
        ver = self._registry.get(self._name)
        planned = plan_request(ver.spec, ver.ladder, feed)
        fut: Future = Future()
        deadline = (time.monotonic() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        req = _Request(planned, fut, deadline, ctx, ts_wall)
        with self._cond:
            if self._closed:
                self._reject_span(ctx, ts_wall, t_sub, "unavailable")
                raise ModelUnavailableError(
                    f"model {self._name!r}: batcher is shut down")
            if self._pending >= self._max_queue:
                self._reject_span(ctx, ts_wall, t_sub, "queue_full")
                self._reject_full()
            self._queues.setdefault(planned.group_key, deque()).append(req)
            self._pending += 1
            self._m_depth.set(self._pending, model=self._name)
            self._cond.notify()
        return fut

    def _reject_span(self, ctx, ts_wall, t_sub, outcome: str):
        """Close the lifecycle span of a request rejected at admission —
        rejections must be visible in the caller's trace, not only in
        the serve_requests_total counter."""
        if ctx is not None:
            _xray.record_span("serve_request", ctx, ts_wall,
                              time.monotonic() - t_sub, cat="serve",
                              model=self._name, outcome=outcome)

    def _reject_full(self):
        self._m_rejects.inc(model=self._name, reason="queue_full")
        self._m_requests.inc(model=self._name, outcome="queue_full")
        raise QueueFullError(
            f"model {self._name!r}: {self._pending} requests "  # race_lint: ignore[unguarded-read] — depth in the error text may be stale by one tick; harmless
            f"already queued (max_queue={self._max_queue}) — "
            f"retry with backoff")

    def queue_depth(self) -> int:
        with self._cond:
            return self._pending

    def _fail(self, req: _Request, exc: ServeError, outcome: str):
        """Fail a request that never ran, tolerating a client cancel():
        transitioning the Future to RUNNING first means set_exception can
        no longer race an InvalidStateError out of the executor thread."""
        if req.future.set_running_or_notify_cancel():
            self._m_requests.inc(model=self._name, outcome=outcome)
            self._req_span(req, outcome)
            req.future.set_exception(exc)
        else:
            self._m_requests.inc(model=self._name, outcome="cancelled")

    def _req_span(self, req: _Request, outcome: str, batch_span=None,
                  **args):
        """Close the request's lifecycle span (submit -> resolution).
        Records straight into the tracer ring (no record_span hop) —
        this runs once per served request on the executor thread,
        BEFORE the future resolves, so every microsecond here delays
        the caller's wakeup (the horizon A/B prices it)."""
        if req.ctx is not None:
            extra = {"model": self._name, "outcome": outcome,
                     "rows": req.planned.rows}
            if batch_span is not None:
                extra["batch_span"] = batch_span
            if args:
                extra.update(args)
            _xray.tracer().record_ctx(
                "serve_request", req.ts_wall,
                time.monotonic() - req.t_enq, "serve", req.ctx, extra)

    # -- executor side ---------------------------------------------------

    def _expire_locked(self, now: float) -> List[_Request]:
        """Pop every queued request whose deadline has passed."""
        dead: List[_Request] = []
        for key in list(self._queues):
            kept: deque = deque()
            for r in self._queues[key]:
                if r.deadline is not None and r.deadline <= now:
                    dead.append(r)
                else:
                    kept.append(r)
            if kept:
                self._queues[key] = kept
            else:
                del self._queues[key]
        self._pending -= len(dead)
        return dead

    def _pop_ready_locked(self, now: float, max_rows: int
                          ) -> Optional[List[_Request]]:
        """Pop a coalesced batch from the oldest-headed READY queue — one
        with enough rows to fill the top rung, or whose head has aged
        past batch_timeout. A full queue runs immediately even while an
        older lone request in another queue is still inside its window."""
        best_key, best_t = None, None
        for key, q in self._queues.items():
            if not q:
                continue
            rows_avail = 0
            for r in q:
                rows_avail += r.planned.rows
                if rows_avail >= max_rows:
                    break
            if rows_avail < max_rows \
                    and now - q[0].t_enq < self._timeout_s:
                continue
            if best_t is None or q[0].t_enq < best_t:
                best_key, best_t = key, q[0].t_enq
        if best_key is None:
            return None
        q = self._queues[best_key]
        batch: List[_Request] = []
        rows = 0
        while q and rows + q[0].planned.rows <= max_rows:
            r = q.popleft()
            batch.append(r)
            rows += r.planned.rows
        if not q:
            del self._queues[best_key]
        self._pending -= len(batch)
        return batch or None

    def _next_wakeup_locked(self, now: float) -> Optional[float]:
        """Seconds until the earliest head matures or ANY queued
        request's deadline expires (a non-head deadline must wake the
        expiry sweep too)."""
        t = None
        for q in self._queues.values():
            if not q:
                continue
            due = q[0].t_enq + self._timeout_s
            for r in q:
                if r.deadline is not None:
                    due = min(due, r.deadline)
            t = due if t is None else min(t, due)
        if t is None:
            return None
        return max(t - now, 1e-4)

    def _loop(self):
        while True:
            with self._cond:
                while not self._closed and self._pending == 0:
                    self._cond.wait()
                if self._closed:
                    return
                now = time.monotonic()
                expired = self._expire_locked(now)
                batch = None
                if self._pending:
                    try:
                        max_rows = self._registry.get(
                            self._name).ladder.max_rows
                    except ServeError:
                        max_rows = 1
                    batch = self._pop_ready_locked(now, max_rows)
                    if batch is None and not expired:
                        self._cond.wait(self._next_wakeup_locked(now))
                self._m_depth.set(self._pending, model=self._name)
            for r in expired:
                self._m_rejects.inc(model=self._name, reason="deadline")
                self._fail(r, DeadlineExceededError(
                    f"model {self._name!r}: deadline expired after "
                    f"{(time.monotonic() - r.t_enq) * 1e3:.1f} ms in "
                    f"queue"), "deadline")
            if batch:
                self._execute(batch)

    def _execute(self, batch: List[_Request]):
        # claim every Future up front: a client cancel() that landed
        # while the request was queued drops it here; after this point
        # set_result/set_exception cannot hit a CANCELLED future
        claimed: List[_Request] = []
        for r in batch:
            if r.future.set_running_or_notify_cancel():
                claimed.append(r)
            else:
                self._m_requests.inc(model=self._name, outcome="cancelled")
        batch = claimed
        if not batch:
            return
        try:
            ver = self._registry.acquire(self._name)
        except ServeError as e:
            for r in batch:
                self._m_requests.inc(model=self._name, outcome="error")
                r.future.set_exception(e)
            return
        try:
            # a hot swap may have SHRUNK the ladder after these requests
            # were admitted: re-chunk the coalesced batch to the acquired
            # version's top rung so valid-when-admitted requests still
            # run; only a single request too big for the new ladder fails
            max_rows = ver.ladder.max_rows
            chunk: List[_Request] = []
            chunk_rows = 0
            for r in batch:
                if r.planned.rows > max_rows:
                    # already RUNNING (claimed above) — safe to set
                    self._m_requests.inc(model=self._name, outcome="error")
                    self._req_span(r, "error", error="BadRequestError")
                    r.future.set_exception(BadRequestError(
                        f"model {self._name!r}: request has "
                        f"{r.planned.rows} rows but a hot swap shrank "
                        f"the ladder to max {max_rows}"))
                    continue
                if chunk and chunk_rows + r.planned.rows > max_rows:
                    self._run_chunk(ver, chunk)
                    chunk, chunk_rows = [], 0
                chunk.append(r)
                chunk_rows += r.planned.rows
            if chunk:
                self._run_chunk(ver, chunk)
        finally:
            self._registry.release(ver)

    def _run_chunk(self, ver, batch: List[_Request]):
        try:
            feeds, rows = concat_requests([r.planned for r in batch])
            target = ver.ladder.rows_rung(rows)
            padded = pad_rows(feeds, rows, target)
            # fluid-xray batch span: the ONE prepared step serving these
            # coalesced requests. Parented to the oldest request's trace
            # (the one that waited longest for this batch); the other
            # members are linked through `traces` and each request's own
            # lifecycle span carries `batch_span` back to it. Computed
            # BEFORE the sparse augment and made AMBIENT around it: the
            # augment's PSClient row pulls run on THIS executor thread,
            # and without the activation they would start fresh traces
            # instead of joining the router -> replica -> pserver chain
            # (fluid-horizon's e2e stitch pins exactly this edge).
            bctx = None
            for r in batch:
                if r.ctx is not None:
                    bctx = _xray.child_of(r.ctx)
                    break
            # ambient activation exists FOR the sparse augment's PSClient
            # spans; a dense model runs nothing that reads the ambient
            # context, so skip the ContextVar set/reset on its hot path
            token = (_xray.set_current(bctx)
                     if bctx is not None and ver.sparse_plan is not None
                     else None)
            try:
                if ver.sparse_plan is not None:
                    # fluid-fleet: pull this BATCH's unique embedding
                    # rows from the pserver shards (row-cache first) and
                    # feed them as fixed-shape sub-tables with ids
                    # remapped — after padding, so the fed shapes match
                    # the warmed signature
                    padded = ver.sparse_plan.augment(padded)
                ts_wall = time.time()
                t0 = time.perf_counter()
                fetches = ver.prepared.run(padded)
                dt = time.perf_counter() - t0
            finally:
                if token is not None:
                    _xray.unset_current(token)
            # a version loaded with warm=False becomes "warmed" by
            # serving (it compiled on demand): /readyz must not report a
            # once-cold-but-now-serving standalone deployment unready
            # forever. Fleet routers still never dispatch to a replica
            # before its first ready verdict, so the AOT-warm contract
            # ("no compiles on routed traffic") holds where it matters.
            ver.warmed = True
            if bctx is not None:
                extra = {"model": self._name, "requests": len(batch),
                         "rows": rows, "padded_rows": target}
                if len(batch) > 1:
                    # cross-links to the other members' traces — when
                    # there IS more than one (a lone request's trace is
                    # already the batch span's parent, and at occupancy
                    # 1 this list would be pure hot-path overhead)
                    extra["traces"] = [r.ctx.trace_id for r in batch[:8]
                                       if r.ctx is not None]
                _xray.tracer().record_ctx("serve_batch", ts_wall, dt,
                                          "serve", bctx, extra)
            self._m_batch_latency.observe(dt * 1e6, model=self._name)
            self._m_occupancy.observe(len(batch), model=self._name)
            self._m_rows.observe(rows, model=self._name)
            self._m_waste.observe((target - rows) / target,
                                  model=self._name)
            self._m_bucket.inc(model=self._name,
                               fit="exact" if target == rows else "padded")
            done = time.monotonic()
            offset = 0
            for r in batch:
                n = r.planned.rows
                outs = [f[offset:offset + n]
                        if getattr(f, "ndim", 0) >= 1
                        and f.shape[0] == target else f
                        for f in fetches]
                offset += n
                self._m_requests.inc(model=self._name, outcome="ok")
                self._m_latency.observe((done - r.t_enq) * 1e6,
                                        model=self._name)
                # batch_span back-links a request to the batch it rode in
                # — only meaningful when it shared the batch (at
                # occupancy 1 the request's own span is the batch span's
                # parent, and resolving bctx.span_id here would pay the
                # lazy-id mint on the hot path for a redundant edge)
                self._req_span(
                    r, "ok",
                    batch_span=(bctx.span_id
                                if bctx is not None and len(batch) > 1
                                else None))
                # fluid-fleet: tag the resolving Future with the version
                # that actually EXECUTED this request — the replica RPC
                # layer returns it so the router's skew gate can prove a
                # coordinated swap produced no mixed-version responses
                r.future.version_id = ver.version_id
                r.future.version_key = ver.version_key
                r.future.set_result(outs)
        except Exception as e:
            for r in batch:
                self._m_requests.inc(model=self._name, outcome="error")
                if not r.future.done():
                    self._req_span(r, "error", error=type(e).__name__)
                    r.future.set_exception(e)

    def reconfigure(self, batch_timeout_ms: Optional[float] = None,
                    max_queue: Optional[int] = None):
        """Apply new batcher settings to the live queues (used when
        add_model re-registers an existing name with explicit values)."""
        with self._cond:
            if batch_timeout_ms is not None:
                self._timeout_s = max(batch_timeout_ms, 0.0) / 1e3
            if max_queue is not None:
                self._max_queue = max_queue
                self._m_qcap.set(max_queue, model=self._name)
            self._cond.notify_all()

    def close(self):
        """Stop the executor thread and fail everything still queued."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            dead = [r for q in self._queues.values() for r in q]
            self._queues.clear()
            self._pending = 0
            # zero the depth gauge too: a frozen last-high value would
            # keep the registry-driven saturation detector firing on a
            # queue that no longer exists
            self._m_depth.set(0, model=self._name)
            self._cond.notify_all()
        for r in dead:
            self._fail(r, ModelUnavailableError(
                f"model {self._name!r}: batcher shut down with the "
                f"request still queued"), "error")
        self._thread.join(timeout=5)
