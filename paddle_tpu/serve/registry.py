"""Hot-swappable model registry: dirs -> warmed PreparedProgram handles.

A served model is a `save_inference_model` dir. The registry turns one
into a `ModelVersion` — its own Scope holding the params, a
`PreparedProgram` handle tagged with the `serving` telemetry source, and
every ladder bucket compiled ahead of traffic — and publishes it behind
an atomic pointer.

Hot swap protocol (rides PR 4's atomic-dir commit: `save_inference_model`
stages the whole dir and swaps it in with renames, so a watcher can
never observe a half-written model):

1. a new version is detected (dir inode/mtime fingerprint changed, or an
   explicit `reload`);
2. the new dir is sha256-verified against its MANIFEST.json and loaded
   into a FRESH scope (`io.load_inference_model(verify=True)`);
3. every bucket of the ladder is warm-compiled — the new version is
   ready to serve its first request at full speed;
4. the published pointer flips under the registry lock — requests that
   acquired the old version finish on it, new acquisitions get the new
   one; a request never sees a half-loaded model;
5. the old version retires once its in-flight refcount drains to zero
   (`ModelVersion.wait_retired` lets tests and drain logic observe it).

Failures in 2-3 leave the old version serving untouched — a corrupt new
dir costs an error log, not an outage.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import io as _io
from ..core.executor import CPUPlace, Executor, Place, Scope
from ..observe import metrics as _metrics
from ..observe import steplog as _steplog
from .bucketing import BucketLadder, feed_spec, warm_feed_shapes
from .errors import ModelNotFoundError, ModelUnavailableError
from .kvcache import PagedKVCache

logger = logging.getLogger(__name__)


def _fingerprint(dirname: str):
    """Identity of the CURRENT committed model dir. save_inference_model
    replaces the whole dir by rename, so a new save = new inode (and new
    mtime); stat of the dir itself is race-free against the swap."""
    st = os.stat(dirname)
    return (st.st_ino, st.st_mtime_ns)


class DecodeModel:
    """fluid-decode sidecar of a generative ModelVersion: the decode-step
    program prepared against the SAME scope as the prefill program (they
    share parameters and the ``*@KV_CACHE`` cache vars), plus the host
    block allocator. Built entirely from the MANIFEST's decode signature
    — no probe request needed to warm-compile."""

    def __init__(self, program, prepared, feed_names, fetch_names,
                 signature: dict, kvcache: PagedKVCache):
        self.program = program
        self.prepared = prepared
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.signature = dict(signature)
        self.kvcache = kvcache


def read_model_manifest(dirname: str) -> dict:
    """The model dir's MANIFEST.json as a dict ({} for legacy dirs or an
    unreadable manifest — verify=True inside the load names the problem
    loudly; this read only routes load-time decisions)."""
    path = os.path.join(dirname, _io.MODEL_MANIFEST)
    if not os.path.isfile(path):
        return {}
    try:
        with open(path) as f:
            return json.load(f) or {}
    except (OSError, json.JSONDecodeError):
        return {}


def read_decode_signature(dirname: str) -> Optional[dict]:
    """The MANIFEST's `decode` key, or None for one-shot (legacy) model
    dirs — those load exactly as before."""
    return read_model_manifest(dirname).get("decode")


def ladder_from_signature(sig: dict) -> BucketLadder:
    """The prefill bucket ladder a decode signature implies: prompt rows
    x prompt-length rungs (block_tables/seq_lens ride the rows dim)."""
    return BucketLadder(rows=tuple(sig["prefill_rows"]),
                        dims={"tokens": {1: tuple(sig["prefill_seq_rungs"])}})


class ModelVersion:
    """One loaded+warmed immutable version of a served model."""

    def __init__(self, name: str, dirname: str, fingerprint,
                 program, feed_names: List[str], fetch_names: List[str],
                 scope: Scope, prepared, ladder: BucketLadder, spec):
        self.name = name
        self.dirname = dirname
        self.fingerprint = fingerprint
        self.program = program
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self.scope = scope
        self.prepared = prepared
        self.ladder = ladder
        self.spec = spec
        self.loaded_at = time.time()
        self.decode: Optional[DecodeModel] = None
        # fluid-fleet: content-addressed identity (sha256 of the dir's
        # MANIFEST.json, which itself names every payload file's sha) —
        # stable across replicas/hosts loading the same push, unlike the
        # inode-based fingerprint; None for legacy manifest-less dirs
        self.manifest_sha: Optional[str] = None
        # fluid-fleet: the serve-time distributed sparse read path (a
        # fleet.sparse.SparseLookupPlan) — feeds prefetched pserver rows
        # under the table names per batch; owns the version-keyed row
        # cache, so a hot swap naturally invalidates by retirement
        self.sparse_plan = None
        # readiness detail for the router's "right version, WARMED" gate:
        # False until every ladder bucket (and the decode step) compiled
        self.warmed = False
        self._refs = 0
        self._retired = False
        self._fully_retired = threading.Event()

    @property
    def generative(self) -> bool:
        return self.decode is not None

    @property
    def version_id(self) -> str:
        return f"{self.fingerprint[0]}:{self.fingerprint[1]}"

    @property
    def version_key(self) -> str:
        """The cross-replica identity: manifest sha when the dir has one
        (content-addressed — two replicas that loaded the same push agree
        on it), else the local fingerprint."""
        return self.manifest_sha or self.version_id

    def retired(self) -> bool:
        return self._fully_retired.is_set()

    def wait_retired(self, timeout: Optional[float] = None) -> bool:
        """Block until this version is both unpublished and drained of
        in-flight requests."""
        return self._fully_retired.wait(timeout)


class _Slot:
    """Published pointer + load config for one model name."""

    def __init__(self, dirname: str, ladder: BucketLadder):
        self.dirname = dirname
        self.ladder = ladder
        self.current: Optional[ModelVersion] = None
        # fluid-fleet coordinated swap: a fully loaded+verified+warmed
        # version staged by prepare() and published only by commit()
        self.staged: Optional[ModelVersion] = None
        # fluid-fleet sparse read path config (duck-typed factory with
        # .build(sparse_meta, version) -> SparseLookupPlan); sticky per
        # slot so the watcher's reloads keep the same wiring
        self.sparse = None


class ModelRegistry:
    def __init__(self, place: Optional[Place] = None,
                 executor: Optional[Executor] = None):
        self._exe = executor or Executor(place or CPUPlace())
        self._lock = threading.Lock()
        self._slots: Dict[str, _Slot] = {}
        self._watcher: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- loading / swapping ----------------------------------------------

    def _slot_for_load(self, name, dirname, ladder, sparse):
        """Resolve (and update) the slot + the manifest-driven load plan
        shared by load() and prepare()."""
        dirname = os.path.abspath(dirname)
        # ONE manifest read per load: the ladder below and the cache
        # sizing in _load_version must come from the same signature (two
        # reads would race a concurrent atomic dir swap into a version
        # whose ladder disagrees with its warmed buckets)
        manifest = read_model_manifest(dirname)
        sig = manifest.get("decode")
        if ladder is None and sig is not None:
            # generative dir + no explicit ladder: the MANIFEST's decode
            # signature names the prefill rows/length rungs — a registry
            # load warm-compiles both programs with no probe request
            ladder = ladder_from_signature(sig)
        with self._lock:
            slot = self._slots.get(name)
            if slot is None:
                slot = self._slots[name] = _Slot(
                    dirname, ladder or BucketLadder())
            else:
                slot.dirname = dirname
                if ladder is not None:
                    slot.ladder = ladder
            if sparse is not None:
                slot.sparse = sparse
        return slot, dirname, manifest

    def load(self, name: str, dirname: str,
             ladder: Optional[BucketLadder] = None,
             warm: bool = True, sparse=None) -> ModelVersion:
        """Load (first call) or hot-swap (subsequent calls) `name` from
        `dirname`. Blocks until the new version is verified, loaded and
        warmed; only then does the published pointer flip. `sparse` wires
        the fleet serve-time sparse read path (see _Slot.sparse)."""
        slot, dirname, manifest = self._slot_for_load(
            name, dirname, ladder, sparse)
        ver = self._load_version(name, dirname, slot.ladder, warm,
                                 manifest, slot.sparse)
        self._publish(name, slot, ver)
        return ver

    def _publish(self, name: str, slot: _Slot, ver: ModelVersion):
        with self._lock:
            old, slot.current = slot.current, ver
            if old is not None:
                old._retired = True
                if old._refs == 0:
                    self._fully_retire_locked(old)
        if old is not None:
            _metrics.counter(
                "serve_hot_swaps_total",
                "model versions atomically swapped in").inc(model=name)
            logger.info("serve: hot-swapped model %r -> version %s "
                        "(old drains %d in-flight)", name, ver.version_id,
                        old._refs)

    # -- fleet coordinated swap: stage now, flip later ---------------------

    def prepare(self, name: str, dirname: Optional[str] = None,
                warm: bool = True) -> ModelVersion:
        """Stage a new version of `name` WITHOUT publishing it: verify,
        load and warm exactly like load(), but park the result so a later
        commit() is a pure pointer flip. The fleet router uses this to
        make the cross-replica flip window milliseconds wide (every
        replica pays its load+warm before ANY replica flips). Re-staging
        replaces (and releases) a previously staged version.

        The slot's published config (dirname, ladder, sparse wiring) is
        NOT touched until commit(): a dir watcher ticking between
        prepare and commit must keep fingerprinting the PUBLISHED dir —
        were slot.dirname moved early, the watcher would unilaterally
        publish the staged (or fleet-ABORTED) version and break the
        coordinated swap's whole point. `name` must already be loaded.

        The staged version's ladder follows the same rule as load():
        a generative dir's NEW decode signature re-derives the prefill
        ladder (the pushed model's rungs, not the old version's — the
        zero-recompile warm contract must hold for the NEW shape set);
        one-shot dirs keep the slot's configured ladder."""
        slot = self._slot(name)
        dirname = os.path.abspath(dirname) if dirname is not None \
            else slot.dirname
        manifest = read_model_manifest(dirname)
        sig = manifest.get("decode")
        ladder = ladder_from_signature(sig) if sig is not None \
            else slot.ladder
        ver = self._load_version(name, dirname, ladder, warm,
                                 manifest, slot.sparse)
        with self._lock:
            prev, slot.staged = slot.staged, ver
        if prev is not None:
            self._discard_staged(prev)
        return ver

    def commit(self, name: str) -> ModelVersion:
        """Publish the staged version (prepare() must have run): the
        atomic pointer flip of the coordinated swap protocol. Only now
        does the slot adopt the staged version's dir and ladder as its
        published config (so the watcher resumes fingerprinting — and
        later reloads re-warm — the right thing)."""
        slot = self._slot(name)
        with self._lock:
            ver, slot.staged = slot.staged, None
            if ver is not None:
                slot.dirname = ver.dirname
                slot.ladder = ver.ladder
        if ver is None:
            raise ModelUnavailableError(
                f"model {name!r}: no staged version to commit — call "
                f"prepare() first")
        self._publish(name, slot, ver)
        return ver

    def abort(self, name: str) -> bool:
        """Discard the staged version (a fleet-wide prepare failed on a
        peer replica; the published version keeps serving untouched)."""
        slot = self._slot(name)
        with self._lock:
            ver, slot.staged = slot.staged, None
        if ver is None:
            return False
        self._discard_staged(ver)
        return True

    @staticmethod
    def _discard_staged(ver: ModelVersion):
        ver._retired = True
        ver._fully_retired.set()
        if ver.decode is not None:
            ver.decode.kvcache.close()
        if ver.sparse_plan is not None:
            ver.sparse_plan.close()

    def staged(self, name: str) -> Optional[ModelVersion]:
        with self._lock:
            slot = self._slots.get(name)
            return slot.staged if slot is not None else None

    def _load_version(self, name, dirname, ladder, warm,
                      manifest=None, sparse=None) -> ModelVersion:
        t0 = time.perf_counter()
        manifest = manifest if manifest is not None \
            else read_model_manifest(dirname)
        sig = manifest.get("decode")
        sparse_meta = manifest.get("sparse")
        if sparse_meta is not None and sig is not None:
            raise ModelUnavailableError(
                f"model dir {dirname}: generative + distributed-sparse "
                f"is not a supported combination")
        if sparse_meta is not None and sparse is None:
            raise ModelUnavailableError(
                f"model dir {dirname} holds its lookup tables "
                f"{sorted(sparse_meta.get('tables', {}))} in pserver "
                f"shards (manifest `sparse` key) — pass "
                f"sparse=fleet.SparseServeConfig(endpoints=...) to "
                f"add_model/load so the replica can prefetch rows")
        fp = _fingerprint(dirname)
        scope = Scope()
        # verify=True: sha256 the whole dir against its MANIFEST before
        # deserializing — a bit-rotted dir raises ModelIntegrityError
        # here and the previously published version keeps serving
        program, feed_names, fetch_vars = _io.load_inference_model(
            dirname, self._exe, scope=scope, verify=True,
            # skip exactly what the saver excluded (the manifest records
            # it: tables + their table-sized optimizer slots); legacy
            # sparse manifests without the list fall back to the tables
            skip_vars=(set(sparse_meta.get("skip_vars")
                           or sparse_meta["tables"])
                       if sparse_meta else None))
        spec = feed_spec(program, feed_names)
        if sig is not None:
            # KV cache state is never serialized (io._is_persistable
            # skips the @KV_CACHE suffix): materialize zeros of the
            # manifest-declared shape BEFORE anything compiles.
            # fluid-torrent int8 residency: int8 cache arrays plus their
            # per-block scale vars and the shared requant counter, all
            # named by the signature
            shape = (sig["num_blocks"], sig["block_size"],
                     sig["num_heads"], sig["head_dim"])
            cache_np = np.int8 if sig.get("kv_dtype") == "int8" \
                else np.float32
            for cname in sig["cache_vars"]:
                scope.set_var(cname, np.zeros(shape, cache_np))
            for sname in (sig.get("scale_vars") or {}).values():
                scope.set_var(sname,
                              np.zeros((sig["num_blocks"],), np.float32))
            if sig.get("requant_var"):
                scope.set_var(sig["requant_var"], np.zeros((1,), np.int32))
        prepared = self._exe.prepare(program, fetch_list=fetch_vars,
                                     scope=scope)
        prepared.telemetry_source = "serving"
        ver = ModelVersion(name, dirname, fp, program, list(feed_names),
                           [v.name for v in fetch_vars], scope, prepared,
                           ladder, spec)
        manifest_path = os.path.join(dirname, _io.MODEL_MANIFEST)
        if os.path.isfile(manifest_path):
            from ..ark.checkpoint import file_sha256
            ver.manifest_sha = file_sha256(manifest_path)
        if sig is not None:
            ver.decode = self._load_decode(ver, sig)
        if sparse_meta is not None:
            # the plan (and its row cache) belongs to THIS version: a hot
            # swap retires the plan with the version — version-keyed
            # cache invalidation by construction
            ver.sparse_plan = sparse.build(sparse_meta, ver)
        if warm:
            self._warm(ver)
            if ver.decode is not None:
                self._warm_decode(ver)
            ver.warmed = True
        _metrics.counter("serve_model_loads_total",
                         "model versions loaded (incl. warmup)").inc(
                             model=name)
        _metrics.histogram(
            "serve_model_load_seconds",
            "load+verify+warm wall time per version").observe(
                time.perf_counter() - t0, model=name)
        return ver

    def _load_decode(self, ver: ModelVersion, sig) -> DecodeModel:
        """Prepare the decode-step program against the version's scope
        (shared params + cache vars) and build its block allocator."""
        loaded = _io.load_decode_program(ver.dirname)
        if loaded is None:
            raise ModelUnavailableError(
                f"model dir {ver.dirname} declares a decode signature in "
                f"its manifest but has no {_io.DECODE_FILENAME} program")
        dprog, dfeeds, dfetches = loaded
        fetch_vars = [dprog.global_block().var(n) for n in dfetches]
        prepared = self._exe.prepare(dprog, fetch_list=fetch_vars,
                                     scope=ver.scope)
        prepared.telemetry_source = "serving"
        kv = PagedKVCache(sig["num_blocks"], sig["block_size"],
                          sig["max_blocks_per_seq"], sig["max_slots"],
                          model=ver.name, version=ver.version_id)
        return DecodeModel(dprog, prepared, dfeeds, dfetches, sig, kv)

    def _warm_decode(self, ver: ModelVersion):
        """Compile the decode step ahead of traffic. The step has exactly
        ONE feed signature (fixed slots, fixed block-table width), so one
        zero-feed run covers every future step — steady-state decode can
        never miss the compile cache."""
        dec = ver.decode
        S = dec.signature["max_slots"]
        feeds = {
            "tokens": np.zeros((S, 1), np.int64),
            "block_tables": np.zeros(
                (S, dec.signature["max_blocks_per_seq"]), np.int32),
            "seq_lens": np.zeros((S,), np.int32),
        }
        dec.prepared.run(feeds)
        _steplog.preseed_shapes(dec.prepared._entry, feeds)

    def _warm(self, ver: ModelVersion):
        """Compile every ladder bucket ahead of traffic. The first run
        binds the entry (`first_call` compile); each further bucket shape
        is recorded as the expected `warmup` cause and pre-seeded into
        the shape tracker, so steady-state traffic on warmed shapes
        produces ZERO recompile events — and any later unwarmed shape
        attributes as `padding_bucket`."""
        warm_feeds = warm_feed_shapes(ver.spec, ver.ladder)
        if ver.sparse_plan is not None:
            # the steady-state signature includes the fed sub-tables:
            # warm with the SAME feed set (zero tables, no RPC), so the
            # first real batch hits the compile cache
            warm_feeds = [ver.sparse_plan.warm_feeds(f) for f in warm_feeds]
        obs = _steplog.observatory()
        for i, feeds in enumerate(warm_feeds):
            if i > 0:
                # the entry exists after the first run; pre-seed BEFORE
                # running so the tracker never counts warmup as a miss
                # (works with the observe flag off too), and record the
                # deliberate compile under its own expected cause
                _steplog.preseed_shapes(ver.prepared._entry, feeds)
                obs.record(ver.program._uid, "warmup", "serving",
                           {"shapes": {n: list(a.shape)
                                       for n, a in feeds.items()}})
            ver.prepared.run(feeds)
        if warm_feeds:
            # the first bucket's signature too (its run may have happened
            # with the observe flag off, never reaching the tracker)
            _steplog.preseed_shapes(ver.prepared._entry, warm_feeds[0])

    def reload(self, name: str, force: bool = False) -> bool:
        """Re-check `name`'s dir; hot-swap if its fingerprint changed (or
        unconditionally with `force`). Returns True when a swap
        happened."""
        slot = self._slot(name)
        fp = _fingerprint(slot.dirname)
        cur = slot.current
        if not force and cur is not None and fp == cur.fingerprint:
            return False
        self.load(name, slot.dirname, ladder=slot.ladder)
        return True

    # -- request-path access ---------------------------------------------

    def _slot(self, name: str) -> _Slot:
        with self._lock:
            slot = self._slots.get(name)
        if slot is None:
            raise ModelNotFoundError(
                f"no model registered as {name!r} "
                f"(registered: {sorted(self._slots)})")
        return slot

    def get(self, name: str) -> ModelVersion:
        """The currently published version (no refcount — use acquire/
        release on the request path)."""
        ver = self._slot(name).current
        if ver is None:
            raise ModelUnavailableError(
                f"model {name!r} has no servable version (load failed or "
                f"in flight)")
        return ver

    def acquire(self, name: str) -> ModelVersion:
        """Pin the current version for one batch: the version cannot
        fully retire until every acquisition is released."""
        with self._lock:
            slot = self._slots.get(name)
            ver = slot.current if slot is not None else None
            if slot is None:
                raise ModelNotFoundError(f"no model registered as {name!r}")
            if ver is None:
                raise ModelUnavailableError(
                    f"model {name!r} has no servable version")
            ver._refs += 1
        return ver

    @staticmethod
    def _fully_retire_locked(ver: ModelVersion):
        """Unpublished AND drained: release observability state too — a
        retired generative version's frozen KV gauges would otherwise
        keep (or mask) the kv_cache_exhaustion verdict forever."""
        ver._fully_retired.set()
        if ver.decode is not None:
            ver.decode.kvcache.close()
        if ver.sparse_plan is not None:
            # drop the retired version's row cache (and its gauges): the
            # swap IS the invalidation — the new version re-pulls rows
            ver.sparse_plan.close()

    def release(self, ver: ModelVersion):
        with self._lock:
            ver._refs -= 1
            if ver._retired and ver._refs == 0:
                self._fully_retire_locked(ver)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._slots)

    # -- dir watching ------------------------------------------------------

    def start_watch(self, interval_s: float = 2.0):
        """Poll every registered model dir; hot-swap on change. Idempotent.
        Polling (not inotify) keeps it dependency-free and works on the
        network filesystems model pushes actually land on."""
        if self._watcher is not None and self._watcher.is_alive():
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(interval_s):
                for name in self.names():
                    try:
                        if self.reload(name):
                            logger.info("serve: watcher swapped %r", name)
                    except Exception as e:
                        # incl. FileNotFoundError in a swap's rename
                        # window and ModelIntegrityError on a bad push —
                        # the published version keeps serving
                        logger.warning("serve: watcher reload of %r "
                                       "failed: %r", name, e)

        self._watcher = threading.Thread(target=_loop, daemon=True,
                                         name="serve-model-watcher")
        self._watcher.start()

    def stop_watch(self):
        self._stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=5)
            self._watcher = None

    def close(self):
        self.stop_watch()
        with self._lock:
            for slot in self._slots.values():
                if slot.staged is not None:
                    self._discard_staged(slot.staged)
                    slot.staged = None
                if slot.current is not None:
                    slot.current._retired = True
                    if slot.current._refs == 0:
                        self._fully_retire_locked(slot.current)
                    elif slot.current.decode is not None:
                        # shutting down with refs still held: zero the
                        # gauges anyway — no more traffic is coming
                        slot.current.decode.kvcache.close()
                slot.current = None
            self._slots.clear()
