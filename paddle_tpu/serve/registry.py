"""Hot-swappable model registry: dirs -> warmed PreparedProgram handles.

A served model is a `save_inference_model` dir. The registry turns one
into a `ModelVersion` — its own Scope holding the params, a
`PreparedProgram` handle tagged with the `serving` telemetry source, and
every ladder bucket compiled ahead of traffic — and publishes it behind
an atomic pointer.

Hot swap protocol (rides PR 4's atomic-dir commit: `save_inference_model`
stages the whole dir and swaps it in with renames, so a watcher can
never observe a half-written model):

1. a new version is detected (dir inode/mtime fingerprint changed, or an
   explicit `reload`);
2. the new dir is sha256-verified against its MANIFEST.json and loaded
   into a FRESH scope (`io.load_inference_model(verify=True)`);
3. every bucket of the ladder is warm-compiled — the new version is
   ready to serve its first request at full speed;
4. the published pointer flips under the registry lock — requests that
   acquired the old version finish on it, new acquisitions get the new
   one; a request never sees a half-loaded model;
5. the old version retires once its in-flight refcount drains to zero
   (`ModelVersion.wait_retired` lets tests and drain logic observe it).

Failures in 2-3 leave the old version serving untouched — a corrupt new
dir costs an error log, not an outage.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import io as _io
from ..core.executor import CPUPlace, Executor, Place, Scope
from ..observe import metrics as _metrics
from ..observe import steplog as _steplog
from .bucketing import BucketLadder, feed_spec, warm_feed_shapes
from .errors import ModelNotFoundError, ModelUnavailableError
from .kvcache import PagedKVCache

logger = logging.getLogger(__name__)


def _fingerprint(dirname: str):
    """Identity of the CURRENT committed model dir. save_inference_model
    replaces the whole dir by rename, so a new save = new inode (and new
    mtime); stat of the dir itself is race-free against the swap."""
    st = os.stat(dirname)
    return (st.st_ino, st.st_mtime_ns)


class DecodeModel:
    """fluid-decode sidecar of a generative ModelVersion: the decode-step
    program prepared against the SAME scope as the prefill program (they
    share parameters and the ``*@KV_CACHE`` cache vars), plus the host
    block allocator. Built entirely from the MANIFEST's decode signature
    — no probe request needed to warm-compile."""

    def __init__(self, program, prepared, feed_names, fetch_names,
                 signature: dict, kvcache: PagedKVCache):
        self.program = program
        self.prepared = prepared
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        self.signature = dict(signature)
        self.kvcache = kvcache


def read_decode_signature(dirname: str) -> Optional[dict]:
    """The MANIFEST's `decode` key, or None for one-shot (legacy) model
    dirs — those load exactly as before."""
    path = os.path.join(dirname, _io.MODEL_MANIFEST)
    if not os.path.isfile(path):
        return None
    try:
        with open(path) as f:
            return json.load(f).get("decode")
    except (OSError, json.JSONDecodeError):
        return None   # verify=True inside the load will name the problem


def ladder_from_signature(sig: dict) -> BucketLadder:
    """The prefill bucket ladder a decode signature implies: prompt rows
    x prompt-length rungs (block_tables/seq_lens ride the rows dim)."""
    return BucketLadder(rows=tuple(sig["prefill_rows"]),
                        dims={"tokens": {1: tuple(sig["prefill_seq_rungs"])}})


class ModelVersion:
    """One loaded+warmed immutable version of a served model."""

    def __init__(self, name: str, dirname: str, fingerprint,
                 program, feed_names: List[str], fetch_names: List[str],
                 scope: Scope, prepared, ladder: BucketLadder, spec):
        self.name = name
        self.dirname = dirname
        self.fingerprint = fingerprint
        self.program = program
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self.scope = scope
        self.prepared = prepared
        self.ladder = ladder
        self.spec = spec
        self.loaded_at = time.time()
        self.decode: Optional[DecodeModel] = None
        self._refs = 0
        self._retired = False
        self._fully_retired = threading.Event()

    @property
    def generative(self) -> bool:
        return self.decode is not None

    @property
    def version_id(self) -> str:
        return f"{self.fingerprint[0]}:{self.fingerprint[1]}"

    def retired(self) -> bool:
        return self._fully_retired.is_set()

    def wait_retired(self, timeout: Optional[float] = None) -> bool:
        """Block until this version is both unpublished and drained of
        in-flight requests."""
        return self._fully_retired.wait(timeout)


class _Slot:
    """Published pointer + load config for one model name."""

    def __init__(self, dirname: str, ladder: BucketLadder):
        self.dirname = dirname
        self.ladder = ladder
        self.current: Optional[ModelVersion] = None


class ModelRegistry:
    def __init__(self, place: Optional[Place] = None,
                 executor: Optional[Executor] = None):
        self._exe = executor or Executor(place or CPUPlace())
        self._lock = threading.Lock()
        self._slots: Dict[str, _Slot] = {}
        self._watcher: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- loading / swapping ----------------------------------------------

    def load(self, name: str, dirname: str,
             ladder: Optional[BucketLadder] = None,
             warm: bool = True) -> ModelVersion:
        """Load (first call) or hot-swap (subsequent calls) `name` from
        `dirname`. Blocks until the new version is verified, loaded and
        warmed; only then does the published pointer flip."""
        dirname = os.path.abspath(dirname)
        # ONE manifest read per load: the ladder below and the cache
        # sizing in _load_version must come from the same signature (two
        # reads would race a concurrent atomic dir swap into a version
        # whose ladder disagrees with its warmed buckets)
        sig = read_decode_signature(dirname)
        if ladder is None and sig is not None:
            # generative dir + no explicit ladder: the MANIFEST's decode
            # signature names the prefill rows/length rungs — a registry
            # load warm-compiles both programs with no probe request
            ladder = ladder_from_signature(sig)
        with self._lock:
            slot = self._slots.get(name)
            if slot is None:
                slot = self._slots[name] = _Slot(
                    dirname, ladder or BucketLadder())
            else:
                slot.dirname = dirname
                if ladder is not None:
                    slot.ladder = ladder
        ver = self._load_version(name, dirname, slot.ladder, warm, sig)
        with self._lock:
            old, slot.current = slot.current, ver
            if old is not None:
                old._retired = True
                if old._refs == 0:
                    self._fully_retire_locked(old)
        if old is not None:
            _metrics.counter(
                "serve_hot_swaps_total",
                "model versions atomically swapped in").inc(model=name)
            logger.info("serve: hot-swapped model %r -> version %s "
                        "(old drains %d in-flight)", name, ver.version_id,
                        old._refs)
        return ver

    def _load_version(self, name, dirname, ladder, warm,
                      sig=None) -> ModelVersion:
        t0 = time.perf_counter()
        fp = _fingerprint(dirname)
        scope = Scope()
        # verify=True: sha256 the whole dir against its MANIFEST before
        # deserializing — a bit-rotted dir raises ModelIntegrityError
        # here and the previously published version keeps serving
        program, feed_names, fetch_vars = _io.load_inference_model(
            dirname, self._exe, scope=scope, verify=True)
        spec = feed_spec(program, feed_names)
        if sig is not None:
            # KV cache state is never serialized (io._is_persistable
            # skips the @KV_CACHE suffix): materialize zeros of the
            # manifest-declared shape BEFORE anything compiles
            shape = (sig["num_blocks"], sig["block_size"],
                     sig["num_heads"], sig["head_dim"])
            for cname in sig["cache_vars"]:
                scope.set_var(cname, np.zeros(shape, np.float32))
        prepared = self._exe.prepare(program, fetch_list=fetch_vars,
                                     scope=scope)
        prepared.telemetry_source = "serving"
        ver = ModelVersion(name, dirname, fp, program, list(feed_names),
                           [v.name for v in fetch_vars], scope, prepared,
                           ladder, spec)
        if sig is not None:
            ver.decode = self._load_decode(ver, sig)
        if warm:
            self._warm(ver)
            if ver.decode is not None:
                self._warm_decode(ver)
        _metrics.counter("serve_model_loads_total",
                         "model versions loaded (incl. warmup)").inc(
                             model=name)
        _metrics.histogram(
            "serve_model_load_seconds",
            "load+verify+warm wall time per version").observe(
                time.perf_counter() - t0, model=name)
        return ver

    def _load_decode(self, ver: ModelVersion, sig) -> DecodeModel:
        """Prepare the decode-step program against the version's scope
        (shared params + cache vars) and build its block allocator."""
        loaded = _io.load_decode_program(ver.dirname)
        if loaded is None:
            raise ModelUnavailableError(
                f"model dir {ver.dirname} declares a decode signature in "
                f"its manifest but has no {_io.DECODE_FILENAME} program")
        dprog, dfeeds, dfetches = loaded
        fetch_vars = [dprog.global_block().var(n) for n in dfetches]
        prepared = self._exe.prepare(dprog, fetch_list=fetch_vars,
                                     scope=ver.scope)
        prepared.telemetry_source = "serving"
        kv = PagedKVCache(sig["num_blocks"], sig["block_size"],
                          sig["max_blocks_per_seq"], sig["max_slots"],
                          model=ver.name, version=ver.version_id)
        return DecodeModel(dprog, prepared, dfeeds, dfetches, sig, kv)

    def _warm_decode(self, ver: ModelVersion):
        """Compile the decode step ahead of traffic. The step has exactly
        ONE feed signature (fixed slots, fixed block-table width), so one
        zero-feed run covers every future step — steady-state decode can
        never miss the compile cache."""
        dec = ver.decode
        S = dec.signature["max_slots"]
        feeds = {
            "tokens": np.zeros((S, 1), np.int64),
            "block_tables": np.zeros(
                (S, dec.signature["max_blocks_per_seq"]), np.int32),
            "seq_lens": np.zeros((S,), np.int32),
        }
        dec.prepared.run(feeds)
        _steplog.preseed_shapes(dec.prepared._entry, feeds)

    def _warm(self, ver: ModelVersion):
        """Compile every ladder bucket ahead of traffic. The first run
        binds the entry (`first_call` compile); each further bucket shape
        is recorded as the expected `warmup` cause and pre-seeded into
        the shape tracker, so steady-state traffic on warmed shapes
        produces ZERO recompile events — and any later unwarmed shape
        attributes as `padding_bucket`."""
        warm_feeds = warm_feed_shapes(ver.spec, ver.ladder)
        obs = _steplog.observatory()
        for i, feeds in enumerate(warm_feeds):
            if i > 0:
                # the entry exists after the first run; pre-seed BEFORE
                # running so the tracker never counts warmup as a miss
                # (works with the observe flag off too), and record the
                # deliberate compile under its own expected cause
                _steplog.preseed_shapes(ver.prepared._entry, feeds)
                obs.record(ver.program._uid, "warmup", "serving",
                           {"shapes": {n: list(a.shape)
                                       for n, a in feeds.items()}})
            ver.prepared.run(feeds)
        if warm_feeds:
            # the first bucket's signature too (its run may have happened
            # with the observe flag off, never reaching the tracker)
            _steplog.preseed_shapes(ver.prepared._entry, warm_feeds[0])

    def reload(self, name: str, force: bool = False) -> bool:
        """Re-check `name`'s dir; hot-swap if its fingerprint changed (or
        unconditionally with `force`). Returns True when a swap
        happened."""
        slot = self._slot(name)
        fp = _fingerprint(slot.dirname)
        cur = slot.current
        if not force and cur is not None and fp == cur.fingerprint:
            return False
        self.load(name, slot.dirname, ladder=slot.ladder)
        return True

    # -- request-path access ---------------------------------------------

    def _slot(self, name: str) -> _Slot:
        with self._lock:
            slot = self._slots.get(name)
        if slot is None:
            raise ModelNotFoundError(
                f"no model registered as {name!r} "
                f"(registered: {sorted(self._slots)})")
        return slot

    def get(self, name: str) -> ModelVersion:
        """The currently published version (no refcount — use acquire/
        release on the request path)."""
        ver = self._slot(name).current
        if ver is None:
            raise ModelUnavailableError(
                f"model {name!r} has no servable version (load failed or "
                f"in flight)")
        return ver

    def acquire(self, name: str) -> ModelVersion:
        """Pin the current version for one batch: the version cannot
        fully retire until every acquisition is released."""
        with self._lock:
            slot = self._slots.get(name)
            ver = slot.current if slot is not None else None
            if slot is None:
                raise ModelNotFoundError(f"no model registered as {name!r}")
            if ver is None:
                raise ModelUnavailableError(
                    f"model {name!r} has no servable version")
            ver._refs += 1
        return ver

    @staticmethod
    def _fully_retire_locked(ver: ModelVersion):
        """Unpublished AND drained: release observability state too — a
        retired generative version's frozen KV gauges would otherwise
        keep (or mask) the kv_cache_exhaustion verdict forever."""
        ver._fully_retired.set()
        if ver.decode is not None:
            ver.decode.kvcache.close()

    def release(self, ver: ModelVersion):
        with self._lock:
            ver._refs -= 1
            if ver._retired and ver._refs == 0:
                self._fully_retire_locked(ver)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._slots)

    # -- dir watching ------------------------------------------------------

    def start_watch(self, interval_s: float = 2.0):
        """Poll every registered model dir; hot-swap on change. Idempotent.
        Polling (not inotify) keeps it dependency-free and works on the
        network filesystems model pushes actually land on."""
        if self._watcher is not None and self._watcher.is_alive():
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(interval_s):
                for name in self.names():
                    try:
                        if self.reload(name):
                            logger.info("serve: watcher swapped %r", name)
                    except Exception as e:
                        # incl. FileNotFoundError in a swap's rename
                        # window and ModelIntegrityError on a bad push —
                        # the published version keeps serving
                        logger.warning("serve: watcher reload of %r "
                                       "failed: %r", name, e)

        self._watcher = threading.Thread(target=_loop, daemon=True,
                                         name="serve-model-watcher")
        self._watcher.start()

    def stop_watch(self):
        self._stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=5)
            self._watcher = None

    def close(self):
        self.stop_watch()
        with self._lock:
            for slot in self._slots.values():
                if slot.current is not None:
                    slot.current._retired = True
                    if slot.current._refs == 0:
                        self._fully_retire_locked(slot.current)
                    elif slot.current.decode is not None:
                        # shutting down with refs still held: zero the
                        # gauges anyway — no more traffic is coming
                        slot.current.decode.kvcache.close()
                slot.current = None
            self._slots.clear()
