"""fluid-decode: the paged KV cache block allocator.

The cache ARRAYS live in the model version's scope as persistable
``*@KV_CACHE`` vars ([num_blocks, block_size, heads, head_dim]) and are
updated in place by the jitted prefill/decode steps (donated like every
other mutable state — see ops/paged_attention.py). This module owns the
HOST side: which physical block belongs to which slot, the free list,
and the block-table array the steps consume.

Design points:

- **Block 0 is reserved (trash).** Inactive slots and prefill padding
  lanes scatter there so every device-side scatter is static; the
  allocator simply never hands block 0 out.
- **Reserve at admission, allocate on append.** Admission reserves the
  worst-case block count for the whole generation (prompt + max new
  tokens), so a running sequence can never strand mid-decode on an empty
  free list — `CacheExhaustedError` is only ever thrown at the admission
  door, where it is retriable backpressure. Physical blocks are popped
  lazily (`ensure`) as the sequence actually grows, and both blocks and
  unused reservation return to the pool on `free_slot` — finish-early
  sequences release capacity immediately.
- **Static block-table array.** One [max_slots, max_blocks_per_seq]
  int32 array, zeroed rows for vacant slots, handed to every step — the
  feed signature never changes, so the decode program compiles exactly
  once.

Occupancy is published as ``serve_kv_blocks_in_use`` (allocated +
reserved, i.e. what admission actually sees) next to
``serve_kv_blocks_capacity``; the ``kv_cache_exhaustion`` health
detector (observe/health.py) fires when the ratio crosses its threshold
— before admissions start bouncing.
"""

from __future__ import annotations

import threading
from typing import List

import numpy as np

from ..observe import metrics as _metrics
from .errors import CacheExhaustedError


def block_residency_nbytes(sig: dict) -> int:
    """Device bytes one cache block costs across every cache var of a
    decode signature — the unit the capacity planner divides a byte
    budget by. fluid-torrent int8 residency pays 1 byte per position
    plus one float32 per-block scale per cache var, vs 4 bytes per
    position for fp32: at the tiny LM's (block_size 4, 2 heads, head_dim
    8) geometry that is 68 vs 256 bytes — ~3.8x more blocks (and
    therefore concurrent sequences) per chip at a fixed budget."""
    per_pos = int(sig["block_size"]) * int(sig["num_heads"]) \
        * int(sig["head_dim"])
    n_caches = len(sig["cache_vars"])
    if sig.get("kv_dtype") == "int8":
        return n_caches * (per_pos + 4)    # int8 values + f32 block scale
    return n_caches * per_pos * 4


def blocks_for_budget(sig: dict, budget_bytes: int) -> int:
    """Allocatable blocks (excluding the trash block) a device byte
    budget affords under `sig`'s residency layout."""
    per_block = block_residency_nbytes(sig)
    return max(int(budget_bytes) // per_block - 1, 0)


class PagedKVCache:
    """Host-side allocator for one model version's paged KV cache."""

    def __init__(self, num_blocks: int, block_size: int,
                 max_blocks_per_seq: int, max_slots: int, model: str = "",
                 version: str = ""):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks must be >= 2 (block 0 is the reserved trash "
                f"block), got {num_blocks}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.max_blocks_per_seq = int(max_blocks_per_seq)
        self.max_slots = int(max_slots)
        self.model = model
        # gauges are labeled (model, version): during a hot swap the OLD
        # version's cache keeps real blocks while in-flight sequences
        # drain — sharing one label would let the new cache's zeros mask
        # a live near-exhaustion incident (and the drain-time frees
        # would clobber the new cache's counts). close() zeroes this
        # version's series when it retires.
        self.version = version
        self._lock = threading.Lock()
        # pop() order ascending (1, 2, ...) — deterministic placement, so
        # block-table contents (and therefore device scatters) replay
        # identically for identical request sequences
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        self._reserved_total = 0
        self._slot_blocks: List[List[int]] = [[] for _ in range(max_slots)]
        self._slot_reserved = [0] * max_slots
        self.block_tables = np.zeros((max_slots, max_blocks_per_seq),
                                     np.int32)
        self._m_in_use = _metrics.gauge(
            "serve_kv_blocks_in_use",
            "paged KV blocks allocated+reserved, per model")
        self._m_capacity = _metrics.gauge(
            "serve_kv_blocks_capacity",
            "allocatable paged KV blocks (excl. trash block), per model")
        self._m_capacity.set(self.capacity, model=model, version=version)
        self._m_in_use.set(0, model=model, version=version)

    @property
    def capacity(self) -> int:
        return self.num_blocks - 1

    def blocks_for(self, n_tokens: int) -> int:
        return -(-max(int(n_tokens), 0) // self.block_size)

    def _publish_locked(self):
        used = sum(len(b) for b in self._slot_blocks) + self._reserved_total
        self._m_in_use.set(used, model=self.model, version=self.version)

    def in_use(self) -> int:
        with self._lock:
            return sum(len(b) for b in self._slot_blocks) \
                + self._reserved_total

    def available(self) -> int:
        with self._lock:
            return len(self._free) - self._reserved_total

    # -- admission / growth ----------------------------------------------

    def reserve(self, slot: int, n_tokens: int):
        """Reserve the worst-case block count for a generation of
        `n_tokens` total tokens. Raises CacheExhaustedError (retriable)
        without reserving anything when the pool can't cover it."""
        need = self.blocks_for(n_tokens)
        if need > self.max_blocks_per_seq:
            raise CacheExhaustedError(
                f"sequence of {n_tokens} tokens needs {need} blocks but "
                f"max_blocks_per_seq is {self.max_blocks_per_seq} — raise "
                f"max_context or reject upstream")
        with self._lock:
            have = len(self._free) - self._reserved_total
            # delta accounting: re-reserving a slot that already holds
            # blocks/reservation (a grow) only charges the difference —
            # and never double-counts the old reservation in the total
            delta = need - len(self._slot_blocks[slot]) \
                - self._slot_reserved[slot]
            if delta > have:
                raise CacheExhaustedError(
                    f"model {self.model!r}: KV cache exhausted — need "
                    f"{need} blocks, {have} available of "
                    f"{self.capacity} (in flight sequences free blocks "
                    f"as they finish; retry with backoff)")
            if delta > 0:
                self._slot_reserved[slot] += delta
                self._reserved_total += delta
            self._publish_locked()

    def ensure(self, slot: int, n_tokens: int) -> np.ndarray:
        """Grow `slot`'s block list to cover `n_tokens` positions,
        drawing from its reservation. Returns the (shared) block-table
        array. Callers must have reserved enough at admission — running
        out here is a bug, not backpressure."""
        need = self.blocks_for(n_tokens)
        with self._lock:
            blocks = self._slot_blocks[slot]
            while len(blocks) < need:
                if self._slot_reserved[slot] <= 0 or not self._free:
                    raise RuntimeError(
                        f"model {self.model!r} slot {slot}: block demand "
                        f"exceeded its admission reservation "
                        f"({len(blocks)} allocated, "
                        f"{self._slot_reserved[slot]} reserved) — "
                        f"admission accounting bug")
                b = self._free.pop()
                self._slot_reserved[slot] -= 1
                self._reserved_total -= 1
                self.block_tables[slot, len(blocks)] = b
                blocks.append(b)
            self._publish_locked()
            return self.block_tables

    def slot_blocks(self, slot: int) -> List[int]:
        """Snapshot of the physical blocks allocated to `slot`, in
        position order — fluid-torrent reads these rows out of the cache
        arrays when extracting a prefilled sequence's KV (and writes a
        wire-delivered payload at them on injection)."""
        with self._lock:
            return list(self._slot_blocks[slot])

    def free_slot(self, slot: int):
        """Return the slot's blocks and any unused reservation to the
        pool and zero its block-table row (vacant rows point at the trash
        block, where inactive-lane scatters land)."""
        with self._lock:
            blocks = self._slot_blocks[slot]
            # ascending free list keeps placement deterministic after
            # recycling too
            self._free.extend(reversed(blocks))
            self._free.sort(reverse=True)
            self._reserved_total -= self._slot_reserved[slot]
            self._slot_reserved[slot] = 0
            self._slot_blocks[slot] = []
            self.block_tables[slot, :] = 0
            self._publish_locked()

    def close(self):
        """Zero THIS version's gauge series: a retired version's cache
        must not keep the exhaustion detector primed with frozen
        occupancy. Capacity is zeroed too so the detector skips the
        retired (model, version) pair entirely."""
        self._m_in_use.set(0, model=self.model, version=self.version)
        self._m_capacity.set(0, model=self.model, version=self.version)
