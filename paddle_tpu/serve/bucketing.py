"""Shape-bucketing planner: pad requests onto a warm compile ladder.

The executor compiles one XLA program per concrete feed-shape signature
(core/executor.py), so an unconstrained request stream — every client
picking its own batch size and sequence length — would recompile per
novel shape, turning a ~100 µs request into a multi-second one. The
planner quantizes every request onto a small LADDER of shapes that the
registry compiles ahead of time at model load:

- the ROWS ladder buckets the batch dim (axis 0, the coalescing axis):
  a batch of 3 coalesced requests pads with zero rows up to the smallest
  rung >= 3;
- per-feed DIM ladders bucket any other dynamic (-1) axis the model
  declares (sequence lengths, variable spatial dims): each request's
  extent pads up to its rung, shared across the batch it joins.

Steady-state traffic therefore produces ONLY already-compiled shapes;
the recompilation observatory (observe/steplog.py) attributes any miss
on a serving handle as `padding_bucket` — a mis-sized ladder, distinct
from a genuine cache bug.

Padding is zeros. For the row-wise programs serving targets (each output
row a function of the same input row — fc/conv/softmax pipelines in
`is_test` mode), padded rows cannot perturb real rows, so sliced outputs
are bit-identical to an unpadded run (pinned by tests/test_serve.py).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core import ir
from .errors import BadRequestError

# rungs double: compile count stays logarithmic in the max batch while
# padding waste is bounded by <2x rows (and far less at occupancy)
DEFAULT_ROWS_LADDER = (1, 2, 4, 8, 16)

# warm-compile combination guard: rows rungs x per-dim rungs multiply
MAX_WARM_BUCKETS = 64


class BucketLadder:
    """The shape quantization config of one served model.

    `rows`: ascending batch-dim rungs; the largest is also the
    micro-batcher's max coalesced batch. `dims`: {feed_name: {axis:
    rungs}} ladders for non-batch dynamic axes (axis counted on the full
    array, so the first sequence axis of a [batch, time, d] feed is 1).
    """

    def __init__(self, rows: Sequence[int] = DEFAULT_ROWS_LADDER,
                 dims: Optional[Dict[str, Dict[int, Sequence[int]]]] = None):
        if not rows or any(r <= 0 for r in rows):
            raise ValueError(f"rows ladder must be positive ints, got {rows!r}")
        self.rows = tuple(sorted(set(int(r) for r in rows)))
        self.dims = {name: {int(ax): tuple(sorted(set(int(r) for r in rungs)))
                            for ax, rungs in axes.items()}
                     for name, axes in (dims or {}).items()}

    @property
    def max_rows(self) -> int:
        return self.rows[-1]

    def rows_rung(self, n: int) -> int:
        """Smallest rung >= n; raises BadRequestError past the ladder."""
        for r in self.rows:
            if r >= n:
                return r
        raise BadRequestError(
            f"request has {n} rows but the ladder tops out at "
            f"{self.max_rows} — split the request or extend the ladder")

    @classmethod
    def from_trace(cls, trace, max_rungs: int = 8, dim_max_rungs: int = 4,
                   max_warm: int = MAX_WARM_BUCKETS) -> "BucketLadder":
        """fluid-planner: derive the ladder FROM TRAFFIC instead of
        hand-configuring it. `trace` is a request-shape trace — the dict
        `load_trace` returns (or a bare list of its ``requests``
        entries), as emitted by `tools/serve_loadgen.py --emit-trace`:
        each request records its row count and the extent of every
        dynamic non-batch axis.

        Rung selection is the exact padding-waste-minimizing partition
        (`analysis.planner.optimal_rungs`): per axis, ≤ `max_rungs`
        (rows) / `dim_max_rungs` (each dynamic dim) rung values
        minimizing total padded units over the trace. The warm-compile
        budget is enforced up front: the rows ladder shrinks until
        rows-rungs × dim-rung combinations fit `max_warm`, so the
        derived ladder always warm-compiles (`warm_feed_shapes` cannot
        raise) and steady-state traffic shaped like the trace produces
        ZERO `padding_bucket` misses.

        Model note: this minimizes REQUEST-level padding. Coalescing
        packs multiple requests per batch, so measured per-batch waste
        under load is at or below this bound (the loadgen drill
        verifies against the observatory)."""
        reqs = trace.get("requests") if isinstance(trace, dict) else trace
        if not reqs:
            raise BadRequestError("from_trace: empty request trace")
        from ..analysis.planner import optimal_rungs

        # per-axis extents, each weighted by the request's CELL count
        # over the other axes (rows x other dims): the DP then minimizes
        # padded cells — predicted_padding_waste's exact objective — not
        # per-axis padded units (which lets a rarely-hit-but-huge axis
        # combination dominate the real waste)
        def _cells(r, skip=None):
            w = float(r["rows"])
            for feed, axes in (r.get("dims") or {}).items():
                for ax, extent in axes.items():
                    if (feed, int(ax)) != skip:
                        w *= int(extent)
            return w

        rows, rows_w = [], []
        for r in reqs:
            rows.append(int(r["rows"]))
            rows_w.append(_cells(r) / max(int(r["rows"]), 1))
        dim_extents: Dict[Tuple[str, int], List[int]] = {}
        dim_weights: Dict[Tuple[str, int], List[float]] = {}
        for r in reqs:
            for feed, axes in (r.get("dims") or {}).items():
                for ax, extent in axes.items():
                    key = (feed, int(ax))
                    dim_extents.setdefault(key, []).append(int(extent))
                    dim_weights.setdefault(key, []).append(
                        _cells(r, skip=key))
        dims: Dict[str, Dict[int, Tuple[int, ...]]] = {}
        combos = 1
        for (feed, ax), extents in sorted(dim_extents.items()):
            rungs = optimal_rungs(extents, dim_max_rungs,
                                  weights=dim_weights[(feed, ax)])
            dims.setdefault(feed, {})[ax] = rungs
            combos *= len(rungs)
        if combos > max_warm:
            raise BadRequestError(
                f"from_trace: {combos} dim-rung combinations exceed the "
                f"{max_warm} warm-compile budget even before the rows "
                f"ladder — lower dim_max_rungs")
        rows_budget = min(int(max_rungs), max(1, max_warm // combos))
        return cls(rows=optimal_rungs(rows, rows_budget, weights=rows_w),
                   dims=dims)

    def dim_rung(self, name: str, axis: int, extent: int) -> int:
        rungs = self.dims.get(name, {}).get(axis)
        if not rungs:
            # no ladder declared for this dynamic axis: serve the extent
            # as-is (each distinct extent is its own compile — the lint
            # and the padding_bucket cause make that visible)
            return extent
        for r in rungs:
            if r >= extent:
                return r
        raise BadRequestError(
            f"feed {name!r} axis {axis} extent {extent} exceeds its "
            f"ladder {rungs} — extend the ladder or reject upstream")


def feed_spec(program: ir.Program, feed_names: Sequence[str]
              ) -> Dict[str, Tuple[Tuple[int, ...], str]]:
    """{feed name: (declared shape, dtype)} for a loaded inference
    program. LoD feeds are rejected: their (data, lengths) @SEQLEN
    expansion is a training-path contract the batcher doesn't model."""
    blk = program.global_block()
    spec = {}
    for name in feed_names:
        v = blk.vars.get(name)
        if v is None:
            raise BadRequestError(
                f"model declares feed {name!r} but the program has no "
                f"such variable")
        if v.lod_level > 0:
            raise BadRequestError(
                f"feed {name!r} is a LoD (variable-length sequence) "
                f"input — not servable through the micro-batcher; pad "
                f"upstream and re-save with lod_level=0")
        spec[name] = (tuple(v.shape), str(v.dtype or "float32"))
    return spec


class PlannedRequest:
    """One request after shape planning: per-feed arrays padded on every
    non-batch dynamic axis, plus the group signature that decides which
    queue (and therefore which coalesced batch) it can join."""

    __slots__ = ("feeds", "rows", "group_key")

    def __init__(self, feeds: Dict[str, np.ndarray], rows: int,
                 group_key: Tuple):
        self.feeds = feeds
        self.rows = rows
        self.group_key = group_key


def plan_request(spec: Dict[str, Tuple[Tuple[int, ...], str]],
                 ladder: BucketLadder,
                 feed: Dict[str, np.ndarray]) -> PlannedRequest:
    """Validate + pad one request's non-batch axes onto the ladder."""
    if set(feed) != set(spec):
        raise BadRequestError(
            f"feed names {sorted(feed)} != model feeds {sorted(spec)}")
    rows = None
    planned: Dict[str, np.ndarray] = {}
    key: List = []
    for name in sorted(spec):
        shape, dtype = spec[name]
        arr = np.asarray(feed[name])
        if arr.ndim != len(shape):
            raise BadRequestError(
                f"feed {name!r} has rank {arr.ndim}, model declares "
                f"rank {len(shape)} ({shape})")
        if rows is None:
            rows = int(arr.shape[0])
            if rows <= 0:
                raise BadRequestError(f"feed {name!r} has zero rows")
        elif arr.shape[0] != rows:
            raise BadRequestError(
                f"feed {name!r} has {arr.shape[0]} rows; other feeds "
                f"have {rows} — batch dims must agree")
        pad = [(0, 0)] * arr.ndim
        padded_tail = []
        for ax in range(1, arr.ndim):
            declared = shape[ax] if ax < len(shape) else -1
            extent = int(arr.shape[ax])
            if declared == -1:
                target = ladder.dim_rung(name, ax, extent)
                pad[ax] = (0, target - extent)
                padded_tail.append(target)
            else:
                if extent != declared:
                    raise BadRequestError(
                        f"feed {name!r} axis {ax} extent {extent} != "
                        f"declared static {declared}")
                padded_tail.append(extent)
        if any(p != (0, 0) for p in pad):
            arr = np.pad(arr, pad)
        if str(arr.dtype) != dtype:
            # mirror DataFeeder's implicit numeric cast so a float64
            # client payload doesn't silently retrace as a new signature
            if arr.dtype.kind in "fiub":
                arr = arr.astype(dtype)
            else:
                raise BadRequestError(
                    f"feed {name!r} dtype {arr.dtype} not castable to "
                    f"declared {dtype}")
        planned[name] = arr
        key.append((name, tuple(padded_tail), dtype))
    # rows above the top rung can never run; reject at the door so the
    # queue doesn't accept work the executor must bounce later
    ladder.rows_rung(rows)
    return PlannedRequest(planned, rows, tuple(key))


def pad_rows(arrays: Dict[str, np.ndarray], rows: int,
             target: int) -> Dict[str, np.ndarray]:
    """Zero-pad every array's axis 0 from `rows` to `target`."""
    if target == rows:
        return arrays
    out = {}
    for name, arr in arrays.items():
        pad = [(0, 0)] * arr.ndim
        pad[0] = (0, target - rows)
        out[name] = np.pad(arr, pad)
    return out


def concat_requests(reqs: Sequence[PlannedRequest]
                    ) -> Tuple[Dict[str, np.ndarray], int]:
    """Coalesce same-group requests along axis 0. Returns (feeds, rows)."""
    if len(reqs) == 1:
        return dict(reqs[0].feeds), reqs[0].rows
    names = reqs[0].feeds.keys()
    feeds = {n: np.concatenate([r.feeds[n] for r in reqs], axis=0)
             for n in names}
    return feeds, sum(r.rows for r in reqs)


TRACE_VERSION = 1


def trace_request(rows: int, dims: Optional[Dict[str, Dict[int, int]]]
                  = None, ts: Optional[float] = None) -> dict:
    """One request-shape trace entry in the `from_trace` format."""
    return {"ts": float(ts or 0.0), "rows": int(rows),
            "dims": {feed: {int(ax): int(e) for ax, e in axes.items()}
                     for feed, axes in (dims or {}).items()}}


def save_trace(path: str, requests: Sequence[dict]) -> None:
    """Write a request-shape trace (`--emit-trace` format): one JSON
    document, `{"version": 1, "requests": [{ts, rows, dims}, ...]}`."""
    with open(path, "w") as f:
        json.dump({"version": TRACE_VERSION,
                   "requests": list(requests)}, f)


def load_trace(path: str) -> dict:
    """Read a `save_trace` document; validates the shape `from_trace`
    consumes and raises BadRequestError naming what is malformed."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "requests" not in doc:
        raise BadRequestError(
            f"trace {path!r}: expected a JSON object with a 'requests' "
            f"list (save_trace / --emit-trace format)")
    for i, r in enumerate(doc["requests"]):
        if not isinstance(r, dict) or "rows" not in r:
            raise BadRequestError(
                f"trace {path!r}: request {i} has no 'rows' field")
    return doc


def predicted_padding_waste(ladder: BucketLadder, trace) -> float:
    """The request-level padded-unit fraction the ladder implies for a
    trace: 1 − Σ(real cells)/Σ(padded cells), counting the rows axis ×
    every traced dynamic axis. This is `from_trace`'s objective — an
    upper-bound-flavored proxy for the batcher's measured per-batch
    `serve_padding_waste_ratio` (coalescing only packs batches fuller)."""
    reqs = trace.get("requests") if isinstance(trace, dict) else trace
    real = padded = 0.0
    for r in reqs:
        rows = int(r["rows"])
        cells, pcells = float(rows), float(ladder.rows_rung(rows))
        for feed, axes in (r.get("dims") or {}).items():
            for ax, extent in axes.items():
                cells *= int(extent)
                pcells *= ladder.dim_rung(feed, int(ax), int(extent))
        real += cells
        padded += pcells
    return 1.0 - real / padded if padded else 0.0


def warm_feed_shapes(spec: Dict[str, Tuple[Tuple[int, ...], str]],
                     ladder: BucketLadder
                     ) -> List[Dict[str, np.ndarray]]:
    """Zero feed dicts covering every (rows rung x dim-rung combo) the
    planner can emit — the ahead-of-time warm set. Combination count is
    capped at MAX_WARM_BUCKETS (a ladder that big is a config smell; the
    registry raises rather than compiling for an hour)."""
    # per-feed resolved tail-shape choices
    per_feed: Dict[str, List[Tuple[int, ...]]] = {}
    for name in sorted(spec):
        shape, _ = spec[name]
        choices: List[List[int]] = [[]]
        for ax in range(1, len(shape)):
            if shape[ax] == -1:
                rungs = ladder.dims.get(name, {}).get(ax)
                if not rungs:
                    raise BadRequestError(
                        f"feed {name!r} axis {ax} is dynamic (-1) but the "
                        f"ladder declares no rungs for it — warmup cannot "
                        f"enumerate its shapes (pass dims={{{name!r}: "
                        f"{{{ax}: (...)}}}})")
                choices = [c + [r] for c in choices for r in rungs]
            else:
                choices = [c + [int(shape[ax])] for c in choices]
        per_feed[name] = [tuple(c) for c in choices]
    # cartesian product across feeds' tail choices x rows rungs
    combos: List[Dict[str, Tuple[int, ...]]] = [{}]
    for name, tails in per_feed.items():
        combos = [dict(c, **{name: t}) for c in combos for t in tails]
        if len(combos) * len(ladder.rows) > MAX_WARM_BUCKETS:
            raise BadRequestError(
                f"bucket ladder enumerates more than {MAX_WARM_BUCKETS} "
                f"warm compiles — shrink the rows/dims ladders")
    out = []
    for rows in ladder.rows:
        for combo in combos:
            out.append({name: np.zeros((rows,) + combo[name],
                                       dtype=spec[name][1])
                        for name in spec})
    return out
