"""fluid-serve: TPU-native inference serving (see docs/SERVING.md).

The north star says this framework must serve heavy traffic; TPU serving
lives or dies on (a) never recompiling on the request path and (b)
keeping the chip fed with full batches. The subsystem is three layers,
each independently testable:

- `serve.registry` — ModelRegistry: loads `save_inference_model` dirs
  (sha256-verified against their MANIFEST.json) into warmed
  PreparedProgram handles, hot-swaps new versions behind an atomic
  pointer, retires old ones after in-flight requests drain;
- `serve.bucketing` — BucketLadder + planner: pads every request onto an
  ahead-of-time-compiled ladder of shapes, so steady-state traffic
  causes ZERO recompiles (the observatory attributes any miss on a
  serving handle as `padding_bucket` — a ladder bug, not a cache bug);
- `serve.batcher` — MicroBatcher: per-bucket queues coalescing
  concurrent requests up to the top rung or `batch_timeout_ms`, bounded
  admission (QueueFullError fast-reject) and per-request deadlines.

`serve.InferenceServer` fronts all three. Load-test with
`tools/serve_loadgen.py`; bench.py records `serve_p50_us`/`serve_p99_us`
/`serve_qps`/`serve_recompiles`.
"""

from __future__ import annotations

from .batcher import MicroBatcher, SlotScheduler  # noqa: F401
from .bucketing import (DEFAULT_ROWS_LADDER, BucketLadder,  # noqa: F401
                        load_trace, plan_request, predicted_padding_waste,
                        save_trace, trace_request, warm_feed_shapes)
from .decode import (DecodeEngine, GenerationResult,  # noqa: F401
                     GenerationStream)
from .errors import (BadRequestError, CacheExhaustedError,  # noqa: F401
                     DeadlineExceededError, KVTransferError,
                     ModelNotFoundError, ModelUnavailableError,
                     QueueFullError, ServeError)
from .kvcache import (PagedKVCache, block_residency_nbytes,  # noqa: F401
                      blocks_for_budget)
from .registry import (DecodeModel, ModelRegistry,  # noqa: F401
                       ModelVersion, read_decode_signature,
                       read_model_manifest)
from .server import InferenceServer, ServeConfig  # noqa: F401
