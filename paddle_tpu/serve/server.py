"""InferenceServer: the in-process serving facade.

Ties the registry (hot-swappable warmed models) to one MicroBatcher per
model and exposes the two request APIs:

    srv = serve.InferenceServer(fluid.CPUPlace())
    srv.add_model("ranker", "/models/ranker",
                  ladder=serve.BucketLadder(rows=(1, 2, 4, 8)))
    out, = srv.infer("ranker", {"x": batch})          # blocking
    fut  = srv.submit("ranker", {"x": batch})         # Future

`infer` blocks on the request's Future; `submit` returns it so callers
can pipeline. Both take `deadline_ms`; `start_watch()` begins polling
every model dir for atomically-pushed new versions. In-process by
design: the RPC transport in front of this (pserver/rpc.py is the
in-repo candidate) only moves bytes — batching, bucketing, swap and
admission semantics all live here and are what the tests pin.
"""

from __future__ import annotations

import time
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FuturesTimeout
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..core.executor import Executor, Place
from ..observe import metrics as _metrics
from .batcher import MicroBatcher
from .bucketing import BucketLadder
from .decode import DecodeEngine, GenerationResult, GenerationStream
from .errors import (BadRequestError, DeadlineExceededError,
                     ModelNotFoundError)
from .registry import ModelRegistry


@dataclass
class ServeConfig:
    """Per-server defaults (overridable per model in add_model)."""

    batch_timeout_ms: float = 2.0     # max wait of a lone request
    max_queue: int = 256              # admission-control bound, requests
    default_deadline_ms: Optional[float] = None
    watch_interval_s: float = 2.0
    # fluid-decode: slot-admission policy for generative models —
    # "continuous" (finished sequences vacate mid-batch, default) or
    # "drain" (classic drain-and-refill; the bench A/B baseline)
    decode_admission: str = "continuous"
    # fluid-torrent rehearsal knobs (tools/ fleet processes): model the
    # compute-bound prefill / memory-bound decode cost split on the CPU
    # test backend — 0.0 disables (see DecodeEngine)
    simulate_prefill_us_per_token: float = 0.0
    simulate_decode_step_us: float = 0.0
    # fluid-pulse opt-in: expose this process's health plane and this
    # server's queue-saturation readiness check on it (0 = ephemeral
    # port; requires the observe flag — start_pulse refuses otherwise)
    pulse_port: Optional[int] = None


class InferenceServer:
    def __init__(self, place: Optional[Place] = None,
                 config: Optional[ServeConfig] = None):
        self.config = config or ServeConfig()
        self._exe = Executor(place) if place is not None else Executor()
        self.registry = ModelRegistry(executor=self._exe)
        self._batchers: Dict[str, MicroBatcher] = {}
        self._engines: Dict[str, DecodeEngine] = {}
        self._closed = False
        self.pulse_port: Optional[int] = None
        self._pulse_check_name: Optional[str] = None
        if self.config.pulse_port is not None:
            from ..observe import health as _health
            from ..observe import pulse as _pulse
            self.pulse_port = _pulse.start_pulse(self.config.pulse_port)
            # instance-scoped name: two servers in one process (blue/green
            # swap, tests) must not clobber each other's check, and
            # close() of one must not unregister the survivor's
            self._pulse_check_name = f"serve_queues@{id(self):x}"
            _health.get_engine().register_check(
                self._pulse_check_name, self._pulse_queue_check,
                ready=True)

    def model_detail(self) -> dict:
        """Per-model readiness detail — ONE shape shared by the pulse
        /readyz check and the fleet replica's `readyz` RPC, so the
        router gates on identical facts whichever transport it polls:
        the active `version` (+ content-addressed `version_key`),
        `warmed` (every ladder bucket compiled — "right version, WARMED"
        is the router's take-traffic condition), queue depth/capacity/
        saturation, and whether the model is generative."""
        detail = {}
        # snapshot: the ticker/scrape thread iterates while add_model may
        # be inserting a batcher from another thread
        for name, b in list(self._batchers.items()):
            depth, cap = b.queue_depth(), max(b._max_queue, 1)
            detail[name] = {"depth": depth, "capacity": cap,
                            "saturation": round(depth / cap, 3),
                            "generative": False, "version": None,
                            "version_key": None, "warmed": False}
        for name, eng in list(self._engines.items()):
            detail[name] = {"depth": None, "capacity": None,
                            "saturation": 0.0, "generative": True,
                            "version": None, "version_key": None,
                            "warmed": False}
        for name, d in detail.items():
            try:
                ver = self.registry.get(name)
            except Exception:
                continue   # mid-load/teardown: version stays None
            d["version"] = ver.version_id
            d["version_key"] = ver.version_key
            d["warmed"] = bool(ver.warmed)
        return detail

    def _pulse_queue_check(self):
        """fluid-pulse /readyz check: per-model queue saturation AND
        per-model version/warm detail (the fleet router's "right
        version, warmed" gate). Unready when any queue saturates —
        sharing the detector's threshold
        (health.SERVE_QUEUE_SATURATION_FRAC) so the two verdicts in one
        /healthz body can't diverge — or when any model's active version
        is not warmed (a router must not send traffic that would compile
        on the request path)."""
        from ..observe.health import SERVE_QUEUE_SATURATION_FRAC
        detail = self.model_detail()
        ok = True
        for d in detail.values():
            if d["saturation"] >= SERVE_QUEUE_SATURATION_FRAC:
                ok = False
            if d["version"] is not None and not d["warmed"]:
                ok = False
        return ok, detail

    # -- model management ------------------------------------------------

    def add_model(self, name: str, dirname: str,
                  ladder: Optional[BucketLadder] = None,
                  batch_timeout_ms: Optional[float] = None,
                  max_queue: Optional[int] = None, warm: bool = True,
                  sparse=None):
        """Load, verify, warm and publish a model, then start its
        executor thread. Calling again with the same name hot-swaps (and
        applies any explicitly passed batcher settings to the live
        batcher). A generative dir (decode signature in its MANIFEST)
        gets a DecodeEngine — generate/submit_stream — instead of a
        one-shot MicroBatcher. `sparse` (fleet.SparseServeConfig) wires
        the serve-time distributed embedding read path for dirs whose
        manifest declares pserver-resident lookup tables."""
        ver = self.registry.load(name, dirname, ladder=ladder, warm=warm,
                                 sparse=sparse)
        # a re-register may change the model's KIND (one-shot <->
        # generative): the stale request path must go, or infer() would
        # keep routing one-shot feeds at a prefill program (and
        # generate() would never find its engine)
        if ver.generative and name in self._batchers:
            self._batchers.pop(name).close()
        if not ver.generative and name in self._engines:
            self._engines.pop(name).close()
        if ver.generative:
            if name not in self._engines:
                self._engines[name] = DecodeEngine(
                    self.registry, name,
                    max_queue=(max_queue if max_queue is not None
                               else self.config.max_queue),
                    admission=self.config.decode_admission,
                    simulate_prefill_us_per_token=(
                        self.config.simulate_prefill_us_per_token),
                    simulate_decode_step_us=(
                        self.config.simulate_decode_step_us))
            return ver
        if name not in self._batchers:
            self._batchers[name] = MicroBatcher(
                self.registry, name,
                batch_timeout_ms=(batch_timeout_ms
                                  if batch_timeout_ms is not None
                                  else self.config.batch_timeout_ms),
                max_queue=(max_queue if max_queue is not None
                           else self.config.max_queue))
        else:
            self._batchers[name].reconfigure(
                batch_timeout_ms=batch_timeout_ms, max_queue=max_queue)
        return self.registry.get(name)

    def reload(self, name: str, force: bool = False) -> bool:
        """Explicit hot-swap check (the watcher calls the same path)."""
        return self.registry.reload(name, force=force)

    # -- fleet coordinated swap (two-phase: stage everywhere, then flip) --

    def prepare_swap(self, name: str, dirname: Optional[str] = None):
        """Stage (verify + load + warm) a new version without publishing
        it; returns the staged ModelVersion. The router runs this on
        every replica BEFORE any replica flips, so commit_swap is a pure
        pointer flip and the fleet's flip window is milliseconds."""
        return self.registry.prepare(name, dirname)

    def commit_swap(self, name: str):
        """Publish the staged version (atomic pointer flip; the old
        version drains via refcount retirement)."""
        return self.registry.commit(name)

    def abort_swap(self, name: str) -> bool:
        """Discard the staged version; the published one keeps serving."""
        return self.registry.abort(name)

    def start_watch(self, interval_s: Optional[float] = None):
        self.registry.start_watch(interval_s if interval_s is not None
                                  else self.config.watch_interval_s)

    # -- request path ----------------------------------------------------

    def submit(self, name: str, feed: Dict[str, np.ndarray],
               deadline_ms: Optional[float] = None) -> Future:
        batcher = self._batchers.get(name)
        if batcher is None:
            if name in self._engines:
                raise BadRequestError(
                    f"model {name!r} is a generative model — use "
                    f"generate/submit_generate/submit_stream, not "
                    f"infer/submit")
            raise ModelNotFoundError(
                f"no model registered as {name!r} "
                f"(registered: {sorted(self._batchers)})")
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        return batcher.submit(feed, deadline_ms=deadline_ms)

    # -- generative request path (fluid-decode) ---------------------------

    def _engine(self, name: str) -> DecodeEngine:
        eng = self._engines.get(name)
        if eng is None:
            if name in self._batchers:
                raise BadRequestError(
                    f"model {name!r} is a one-shot inference model — use "
                    f"infer/submit, not generate")
            raise ModelNotFoundError(
                f"no generative model registered as {name!r} "
                f"(registered: {sorted(self._engines)})")
        return eng

    def generate(self, name: str, prompt,
                 max_new_tokens: int = 16,
                 deadline_ms: Optional[float] = None) -> GenerationResult:
        """Blocking autoregressive generation (greedy). Returns a
        GenerationResult; retriable backpressure raises QueueFullError /
        CacheExhaustedError immediately."""
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        return self._engine(name).generate(
            prompt, max_new_tokens=max_new_tokens, deadline_ms=deadline_ms)

    def submit_generate(self, name: str, prompt,
                        max_new_tokens: int = 16,
                        deadline_ms: Optional[float] = None) -> Future:
        """Non-blocking generation: returns the Future of its
        GenerationResult."""
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        return self._engine(name).submit(
            prompt, max_new_tokens=max_new_tokens, deadline_ms=deadline_ms)

    def submit_stream(self, name: str, prompt,
                      max_new_tokens: int = 16,
                      deadline_ms: Optional[float] = None
                      ) -> GenerationStream:
        """Streaming generation: iterate the returned stream for tokens
        as they decode; stream.future resolves to the GenerationResult."""
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        return self._engine(name).submit(
            prompt, max_new_tokens=max_new_tokens, deadline_ms=deadline_ms,
            stream=True)

    # -- disaggregated halves (fluid-torrent) ------------------------------

    def submit_prefill(self, name: str, prompt,
                       deadline_ms: Optional[float] = None) -> Future:
        """Prefill half: run the prompt's prefill step only. The Future
        resolves to a GenerationResult whose `kv` carries the extracted
        KV payload and whose single token seeds the decode half."""
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        return self._engine(name).submit(
            prompt, deadline_ms=deadline_ms, prefill_only=True)

    def submit_prefilled(self, name: str, prompt, first_token: int,
                         kv: dict, max_new_tokens: int = 16,
                         deadline_ms: Optional[float] = None) -> Future:
        """Decode half: inject a KV payload prefilled elsewhere and run
        the rest of the generation here. Returns the Future of the full
        GenerationResult (its tokens start with `first_token`)."""
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        return self._engine(name).submit_prefilled(
            prompt, first_token, kv, max_new_tokens=max_new_tokens,
            deadline_ms=deadline_ms)

    def infer(self, name: str, feed: Dict[str, np.ndarray],
              deadline_ms: Optional[float] = None) -> List[np.ndarray]:
        """Synchronous request: returns the fetch list (row-sliced back
        to this request's rows)."""
        if deadline_ms is None:
            deadline_ms = self.config.default_deadline_ms
        fut = self.submit(name, feed, deadline_ms=deadline_ms)
        if deadline_ms is None:
            return fut.result()
        # the batcher enforces the QUEUED deadline; the slack covers a
        # batch already on the chip when the deadline strikes
        # _FuturesTimeout: on Python < 3.11 concurrent.futures raises its
        # OWN TimeoutError class, not the builtin
        try:
            return fut.result(timeout=deadline_ms / 1e3 + 30.0)
        except (TimeoutError, _FuturesTimeout):
            raise DeadlineExceededError(
                f"model {name!r}: no result within deadline "
                f"{deadline_ms} ms (+30 s execution slack)") from None

    # -- introspection ---------------------------------------------------

    def stats(self) -> dict:
        """Serving-metric snapshot (the observe registry holds the same
        numbers in exportable form)."""
        out: dict = {"models": {}, "ts": time.time()}
        for name, b in self._batchers.items():
            ver = None
            try:
                ver = self.registry.get(name)
            except Exception:
                pass
            occ = _metrics.histogram("serve_batch_occupancy").summary(
                model=name)
            lat = _metrics.histogram("serve_request_latency_us").summary(
                model=name)
            waste = _metrics.histogram("serve_padding_waste_ratio").summary(
                model=name)
            out["models"][name] = {
                "version": ver.version_id if ver else None,
                "loaded_at": ver.loaded_at if ver else None,
                "queue_depth": b.queue_depth(),
                "batches": occ["count"] if occ else 0,
                "avg_occupancy": round(occ["mean"], 3) if occ else 0.0,
                "avg_latency_us": round(lat["mean"], 1) if lat else 0.0,
                "avg_padding_waste": round(waste["mean"], 4)
                    if waste else 0.0,
                "requests": {
                    outcome: _metrics.counter("serve_requests_total").value(
                        model=name, outcome=outcome)
                    for outcome in ("ok", "error", "deadline", "queue_full")
                },
            }
        for name, eng in self._engines.items():
            ver = None
            try:
                ver = self.registry.get(name)
            except Exception:
                pass
            entry = {"version": ver.version_id if ver else None,
                     "generative": True}
            entry.update(eng.stats())
            out["models"][name] = entry
        return out

    def close(self):
        if self._closed:
            return
        self._closed = True
        if self._pulse_check_name is not None:
            from ..observe import health as _health
            _health.get_engine().unregister_check(self._pulse_check_name)
            self._pulse_check_name = None
            self.pulse_port = None
        for b in self._batchers.values():
            b.close()
        self._batchers.clear()
        for e in self._engines.values():
            e.close()
        self._engines.clear()
        self.registry.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
