"""Small graphviz dot-building library (reference
python/paddle/fluid/graphviz.py: Graph/Node/Edge/Rank +
GraphPreviewGenerator). Pure text generation — rendering shells out to
`dot` only if present; `show()` always writes the .dot source so the
capability works in sandboxes without graphviz installed."""

from __future__ import annotations

import subprocess


def crepr(v):
    return f'"{v}"' if isinstance(v, str) else repr(v)


class Rank:
    def __init__(self, kind, name, priority):
        if kind not in ("source", "sink", "same", "min", "max"):
            raise ValueError(f"invalid rank kind {kind!r}")
        self.kind = kind
        self.name = name
        self.priority = priority
        self.nodes = []

    def __str__(self):
        if not self.nodes:
            return ""
        return "{" + f"rank={self.kind};" + ",".join(
            n.name for n in self.nodes) + "}"


class Node:
    counter = 1

    def __init__(self, label, prefix, description="", **attrs):
        self.label = label
        self.name = "%s_%d" % (prefix, Node.counter)
        Node.counter += 1
        self.description = description
        self.attrs = attrs

    def __str__(self):
        attrs = ",".join(f"{k}={crepr(v)}" for k, v in
                         ({"label": self.label, **self.attrs}).items())
        return f"{self.name} [{attrs}]"


class Edge:
    def __init__(self, source, target, **attrs):
        self.source = source
        self.target = target
        self.attrs = attrs

    def __str__(self):
        attrs = ",".join(f"{k}={crepr(v)}" for k, v in self.attrs.items())
        return f"{self.source.name}->{self.target.name}" + (
            f" [{attrs}]" if attrs else "")


class Graph:
    rank_counter = 0

    def __init__(self, title, **attrs):
        self.title = title
        self.attrs = attrs
        self.nodes = []
        self.edges = []
        self.rank_groups = {}

    def code(self):
        return self.__str__()

    def rank_group(self, kind, priority):
        name = f"rankgroup-{Graph.rank_counter}"
        Graph.rank_counter += 1
        self.rank_groups[name] = Rank(kind, name, priority)
        return name

    def node(self, label, prefix, description="", **attrs):
        node = Node(label, prefix, description, **attrs)
        if "rank" in attrs:
            self.rank_groups[attrs.pop("rank")].nodes.append(node)
            node.attrs.pop("rank", None)
        self.nodes.append(node)
        return node

    def edge(self, source, target, **attrs):
        edge = Edge(source, target, **attrs)
        self.edges.append(edge)
        return edge

    def compile(self, dot_path):
        """Write dot source; render a PDF beside it when `dot` exists."""
        with open(dot_path, "w") as f:
            f.write(self.code())
        out = dot_path.rsplit(".", 1)[0] + ".pdf"
        try:
            subprocess.run(["dot", "-Tpdf", dot_path, "-o", out],
                           check=True, capture_output=True)
            return out
        except (OSError, subprocess.CalledProcessError):
            return dot_path

    def show(self, dot_path):
        return self.compile(dot_path)

    def _rank_repr(self):
        return "\n".join(str(g) for g in
                         sorted(self.rank_groups.values(),
                                key=lambda x: x.priority))

    def __str__(self):
        name = "".join(c if c.isalnum() or c == "_" else "_"
                       for c in str(self.title)) or "G"
        lines = [f"digraph {name} {{"]
        lines += [f"{k}={crepr(v)};" for k, v in self.attrs.items()]
        lines += [str(n) for n in self.nodes]
        lines += [str(e) for e in self.edges]
        rank = self._rank_repr()
        if rank:
            lines.append(rank)
        lines.append("}")
        return "\n".join(lines)


class GraphPreviewGenerator:
    """Convenience wrapper for op/param/data-node styling (reference
    graphviz.py:179)."""

    def __init__(self, title):
        self.graph = Graph(title)

    def add_param(self, name, data_type, highlight=False):
        return self.graph.node(
            f"{name}\\n{data_type}", prefix="param", shape="box",
            style="filled",
            fillcolor="yellow" if highlight else "lightgray")

    def add_op(self, opType, **kwargs):
        return self.graph.node(opType, prefix="op", shape="ellipse",
                               style="filled", fillcolor="lightblue",
                               **kwargs)

    def add_arg(self, name, highlight=False):
        return self.graph.node(name, prefix="arg", shape="box",
                               fillcolor="orange" if highlight else "white",
                               style="filled")

    def add_edge(self, source, target, **kwargs):
        return self.graph.edge(source, target, **kwargs)

    def __call__(self, path, show=False):
        if show:
            return self.graph.show(path)
        return self.graph.compile(path)
