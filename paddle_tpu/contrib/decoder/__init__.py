"""`fluid.contrib.decoder` (reference contrib/decoder/__init__.py)."""

from . import beam_search_decoder  # noqa: F401
from .beam_search_decoder import (InitState, StateCell, TrainingDecoder,  # noqa: F401
                                  BeamSearchDecoder)

__all__ = ["beam_search_decoder", "InitState", "StateCell",
           "TrainingDecoder", "BeamSearchDecoder"]
