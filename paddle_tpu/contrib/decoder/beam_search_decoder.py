"""General RNN decoder API: training + beam-search inference
(reference python/paddle/fluid/contrib/decoder/beam_search_decoder.py).

API parity: ``InitState``, ``StateCell``, ``TrainingDecoder``,
``BeamSearchDecoder`` with the reference's state-machine contract — a
``StateCell`` owns named hidden states and step inputs, a user-supplied
``state_updater`` computes the next state, ``TrainingDecoder`` runs the
cell over teacher-forced step inputs, ``BeamSearchDecoder`` runs it in
generation mode and beam-searches the output distribution.

TPU-native redesign: the reference drives generation with a ``While`` op
over LoD tensor arrays whose beam width shrinks as hypotheses finish
(dynamic shapes). Here generation is a bounded ``StaticRNN`` scan over
``max_len`` steps on dense ``[batch, beam]`` state — finished beams are
masked inside ``beam_search_step`` (ops/beam.py) instead of being pruned
from the tensor, so every step is a fixed-shape XLA program. The
training path lowers to the same masked ``lax.scan`` as ``DynamicRNN``.
"""

from __future__ import annotations

import contextlib

from ... import layers
from ...core import ir
from ...layer_helper import LayerHelper
from ...models.machine_translation import (tile_beam, batch_gather,
                                           beam_search_step, beam_backtrack,
                                           _log_softmax)

__all__ = ["InitState", "StateCell", "TrainingDecoder", "BeamSearchDecoder"]


class _DecoderType:
    TRAINING = 1
    BEAM_SEARCH = 2


@contextlib.contextmanager
def _in_parent_block(rnn):
    """Build ops in the StaticRNN's parent block while inside its step
    block — memory inits must live outside the scan body."""
    program = rnn.helper.main_program
    cur = program._current_block_idx
    program._current_block_idx = rnn._parent_block.idx
    try:
        yield
    finally:
        program._current_block_idx = cur


class InitState:
    """Initial hidden state (reference beam_search_decoder.py InitState).

    Either wraps an existing variable, or creates a constant-filled one
    shaped like ``init_boot``'s batch. ``need_reorder`` is accepted for
    API parity; the dense [batch, beam] layout keeps batch rows aligned,
    so no rank-table reorder is ever needed.
    """

    def __init__(self, init=None, shape=None, value=0.0, init_boot=None,
                 need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "init_boot must be provided to infer the shape of InitState")
        else:
            self._init = layers.fill_constant_batch_size_like(
                init_boot, [-1] + list(shape), dtype, value)
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class _MemoryState:
    """Training-mode state storage: a DynamicRNN memory."""

    def __init__(self, state_name, rnn_obj, init_state):
        self._state_name = state_name
        self._rnn_obj = rnn_obj
        self._state_mem = self._rnn_obj.memory(
            init=init_state.value, need_reorder=init_state.need_reorder)

    def get_state(self):
        return self._state_mem

    def update_state(self, state):
        self._rnn_obj.update_memory(self._state_mem, state)


class _BeamState:
    """Beam-mode state storage: a StaticRNN memory carried as
    [batch*beam, ...]; the decoder reorders it by parent beam after each
    selection step (the static analog of the reference's
    sequence_expand-by-prev_scores)."""

    def __init__(self, state_name, decoder, init_state):
        self._state_name = state_name
        self._decoder = decoder
        with _in_parent_block(decoder._rnn):
            tiled = tile_beam(init_state.value, decoder._beam_size)
        self._state_mem = decoder._rnn.memory(init=tiled)
        self._pending = None

    def get_state(self):
        return self._state_mem

    def update_state(self, state):
        # actual update_memory happens in the decoder once the step's
        # parent selection is known (decode() applies batch_gather)
        self._pending = state


class StateCell:
    """Hidden-state container + updater for RNN decoding (reference
    beam_search_decoder.py StateCell). States are declared as InitState
    objects; the ``state_updater`` callback computes the next state from
    the current states and step inputs each decode step."""

    def __init__(self, inputs, states, out_state, name=None):
        self._helper = LayerHelper("state_cell", name=name)
        self._cur_states = {}
        self._state_names = []
        for state_name, state in states.items():
            if not isinstance(state, InitState):
                raise ValueError("state must be an InitState object")
            self._cur_states[state_name] = state
            self._state_names.append(state_name)
        self._inputs = inputs
        self._cur_decoder_obj = None
        self._in_decoder = False
        self._states_holder = {}
        self._switched_decoder = False
        self._state_updater = None
        self._out_state = out_state
        if self._out_state not in self._cur_states:
            raise ValueError("out_state must be one state in states")

    def _enter_decoder(self, decoder_obj):
        if self._in_decoder or self._cur_decoder_obj is not None:
            raise ValueError("StateCell has already entered a decoder.")
        self._in_decoder = True
        self._cur_decoder_obj = decoder_obj
        self._switched_decoder = False

    def _leave_decoder(self, decoder_obj):
        if not self._in_decoder:
            raise ValueError("StateCell not in decoder, invalid leave.")
        if self._cur_decoder_obj is not decoder_obj:
            raise ValueError("Inconsistent decoder object in StateCell.")
        self._in_decoder = False
        self._cur_decoder_obj = None
        self._switched_decoder = False

    def _switch_decoder(self):
        if not self._in_decoder:
            raise ValueError("StateCell must enter a decoder first.")
        if self._switched_decoder:
            raise ValueError("StateCell already done switching.")
        for state_name in self._state_names:
            if state_name not in self._states_holder:
                state = self._cur_states[state_name]
                if not isinstance(state, InitState):
                    raise ValueError(
                        f"state {state_name} should be an InitState object")
                self._states_holder[state_name] = {}
                if self._cur_decoder_obj.type == _DecoderType.TRAINING:
                    holder = _MemoryState(
                        state_name, self._cur_decoder_obj.dynamic_rnn, state)
                elif self._cur_decoder_obj.type == _DecoderType.BEAM_SEARCH:
                    holder = _BeamState(
                        state_name, self._cur_decoder_obj, state)
                else:
                    raise ValueError("Unknown decoder type")
                self._states_holder[state_name][
                    id(self._cur_decoder_obj)] = holder
            self._cur_states[state_name] = self._states_holder[state_name][
                id(self._cur_decoder_obj)].get_state()
        self._switched_decoder = True

    def get_state(self, state_name):
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        if state_name not in self._cur_states:
            raise ValueError(f"Unknown state {state_name}")
        return self._cur_states[state_name]

    def get_input(self, input_name):
        if input_name not in self._inputs or self._inputs[input_name] is None:
            raise ValueError(f"Invalid input {input_name}.")
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        self._cur_states[state_name] = state_value

    def state_updater(self, updater):
        self._state_updater = updater

        def _decorator(state_cell):
            if state_cell is self:
                raise TypeError("Updater should only accept a StateCell "
                                "object as argument.")
            updater(state_cell)

        return _decorator

    def compute_state(self, inputs):
        """Feed the step inputs and run the updater (reference
        StateCell.compute_state)."""
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        for input_name, input_value in inputs.items():
            if input_name not in self._inputs:
                raise ValueError(
                    f"Unknown input {input_name}: not a declared step input")
            self._inputs[input_name] = input_value
        self._state_updater(self)

    def update_states(self):
        """Record the new state values after a step (reference
        StateCell.update_states)."""
        if self._in_decoder and not self._switched_decoder:
            self._switch_decoder()
        for state_name, decoder_state in self._states_holder.items():
            if id(self._cur_decoder_obj) not in decoder_state:
                raise ValueError("Unknown decoder object; make sure "
                                 "switch_decoder has been invoked.")
            decoder_state[id(self._cur_decoder_obj)].update_state(
                self._cur_states[state_name])

    def out_state(self):
        return self._cur_states[self._out_state]


class TrainingDecoder:
    """Teacher-forced decoder (reference beam_search_decoder.py
    TrainingDecoder): wraps a DynamicRNN; the user's block reads step
    inputs, computes the cell, and declares outputs."""

    BEFORE_DECODER = 0
    IN_DECODER = 1
    AFTER_DECODER = 2

    def __init__(self, state_cell, name=None):
        self._helper = LayerHelper("training_decoder", name=name)
        self._status = TrainingDecoder.BEFORE_DECODER
        self._dynamic_rnn = layers.DynamicRNN()
        self._type = _DecoderType.TRAINING
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)

    @contextlib.contextmanager
    def block(self):
        if self._status != TrainingDecoder.BEFORE_DECODER:
            raise ValueError("decoder.block() can only be invoked once")
        self._status = TrainingDecoder.IN_DECODER
        with self._dynamic_rnn.block():
            yield
        self._status = TrainingDecoder.AFTER_DECODER
        self._state_cell._leave_decoder(self)

    @property
    def state_cell(self):
        self._assert_in_decoder_block("state_cell")
        return self._state_cell

    @property
    def dynamic_rnn(self):
        return self._dynamic_rnn

    @property
    def type(self):
        return self._type

    def step_input(self, x):
        self._assert_in_decoder_block("step_input")
        return self._dynamic_rnn.step_input(x)

    def static_input(self, x):
        self._assert_in_decoder_block("static_input")
        return self._dynamic_rnn.static_input(x)

    def __call__(self, *args, **kwargs):
        if self._status != TrainingDecoder.AFTER_DECODER:
            raise ValueError("Output of training decoder can only be "
                             "visited outside the block.")
        return self._dynamic_rnn(*args, **kwargs)

    def output(self, *outputs):
        self._assert_in_decoder_block("output")
        self._dynamic_rnn.output(*outputs)

    def _assert_in_decoder_block(self, method):
        if self._status != TrainingDecoder.IN_DECODER:
            raise ValueError(f"{method} should be invoked inside block of "
                             "TrainingDecoder object.")


class BeamSearchDecoder:
    """Generation-mode decoder with beam search (reference
    beam_search_decoder.py BeamSearchDecoder).

    Static-beam redesign: a bounded StaticRNN of ``max_len`` steps carries
    ``[batch, beam]`` ids/scores/finished plus the cell states tiled to
    ``[batch*beam, ...]``; each step embeds the previous ids, runs the
    user's state updater, projects the out-state to vocab log-probs, and
    applies ``beam_search_step`` + parent-gather instead of the
    reference's LoD ``beam_search`` op + shrinking While loop.
    ``topk_size`` is accepted for API parity (the dense kernel ranks the
    full vocabulary — a GPU pre-pruning knob has no TPU benefit).
    """

    BEFORE_BEAM_SEARCH_DECODER = 0
    IN_BEAM_SEARCH_DECODER = 1
    AFTER_BEAM_SEARCH_DECODER = 2

    def __init__(self, state_cell, init_ids, init_scores, target_dict_dim,
                 word_dim, input_var_dict=None, topk_size=50, sparse_emb=True,
                 max_len=100, beam_size=1, end_id=1, name=None):
        self._helper = LayerHelper("beam_search_decoder", name=name)
        self._type = _DecoderType.BEAM_SEARCH
        self._status = BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._max_len = max_len
        self._beam_size = beam_size
        self._end_id = end_id
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._topk_size = topk_size
        self._sparse_emb = sparse_emb
        self._word_dim = word_dim
        self._input_var_dict = input_var_dict or {}
        self._rnn = layers.StaticRNN(name=(name or "bsd") + "_rnn",
                                     num_steps=max_len)
        self._ids_mem = None
        self._scores_mem = None
        self._fin_mem = None
        self._step_results = None
        self._final = None

    @contextlib.contextmanager
    def block(self):
        """One decode step (the StaticRNN step body)."""
        if self._status != BeamSearchDecoder.BEFORE_BEAM_SEARCH_DECODER:
            raise ValueError("block() can only be invoked once.")
        self._status = BeamSearchDecoder.IN_BEAM_SEARCH_DECODER
        with self._rnn.step():
            yield
        self._status = BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER
        self._state_cell._leave_decoder(self)

    @property
    def type(self):
        return self._type

    @property
    def state_cell(self):
        self._assert_in_decoder_block("state_cell")
        return self._state_cell

    def early_stop(self):
        """API parity no-op: finished beams are masked inside
        beam_search_step (they accumulate nothing and re-emit end_id), so
        a fully-finished batch coasts through the remaining bounded steps
        with unchanged results instead of breaking the loop."""

    def _init_beam_memories(self):
        """ids/scores/finished memories, [batch, beam]."""
        K = self._beam_size
        with _in_parent_block(self._rnn):
            ids0 = layers.cast(
                layers.reshape(tile_beam(
                    layers.reshape(self._init_ids, shape=[-1, 1]), K),
                    shape=[-1, K]), "int32")
            import numpy as np
            # only beam 0 live at step 0, else all beams duplicate the
            # same hypothesis K times
            first_active = layers.assign(
                np.array([0.0] + [-1e9] * (K - 1), np.float32))
            s0 = layers.reshape(tile_beam(
                layers.cast(layers.reshape(self._init_scores,
                                           shape=[-1, 1]), "float32"), K),
                shape=[-1, K])
            scores0 = layers.elementwise_add(s0, first_active, axis=-1)
            fin0 = layers.cast(layers.elementwise_mul(
                layers.cast(ids0, "float32"),
                layers.fill_constant(shape=[1], dtype="float32", value=0.0)),
                "bool")
        self._ids_mem = self._rnn.memory(init=ids0)
        self._scores_mem = self._rnn.memory(init=scores0)
        self._fin_mem = self._rnn.memory(init=fin0)

    def decode(self):
        """The standard decode loop (reference BeamSearchDecoder.decode)."""
        V, K, E = self._target_dict_dim, self._beam_size, self._word_dim
        with self.block():
            self._init_beam_memories()
            prev_ids = self._ids_mem                     # [B, K]
            prev_scores = self._scores_mem               # [B, K]
            flat_ids = layers.reshape(prev_ids, shape=[-1, 1])
            emb = layers.embedding(layers.cast(flat_ids, "int64"),
                                   size=[V, E], dtype="float32",
                                   is_sparse=self._sparse_emb)
            prev_ids_embedding = (layers.squeeze(emb, axes=[1])
                                  if len(emb.shape) == 3 else emb)

            feed_dict = {}
            for name, var in self._input_var_dict.items():
                if name not in self._state_cell._inputs:
                    raise ValueError(f"Variable {name} not found in "
                                     "StateCell!")
                # constant across steps and identical across a batch's
                # beams: tile once (static analog of per-step
                # sequence_expand by prev_scores)
                with _in_parent_block(self._rnn):
                    feed_dict[name] = tile_beam(var, K)
            for name in self._state_cell._inputs:
                if name not in feed_dict:
                    feed_dict[name] = prev_ids_embedding

            self._state_cell.compute_state(inputs=feed_dict)
            current_state = self._state_cell.out_state()   # [B*K, H]
            logits = layers.fc(input=current_state, size=V, act=None)
            logp = _log_softmax(logits)
            logp3 = layers.reshape(logp, shape=[-1, K, V])
            new_ids, parents, new_scores, new_fin = beam_search_step(
                logp3, prev_scores, self._fin_mem, beam_size=K,
                end_id=self._end_id)

            self._state_cell.update_states()
            for holders in self._state_cell._states_holder.values():
                st = holders[id(self)]
                if st._pending is None:
                    continue
                shp = [-1, K] + [int(d) for d in st._pending.shape[1:]]
                sel = batch_gather(
                    layers.reshape(st._pending, shape=shp), parents)
                flat = [-1] + [int(d) for d in st._pending.shape[1:]]
                self._rnn.update_memory(
                    st._state_mem, layers.reshape(sel, shape=flat))
                st._pending = None
            self._rnn.update_memory(self._ids_mem, new_ids)
            self._rnn.update_memory(self._scores_mem, new_scores)
            self._rnn.update_memory(self._fin_mem, new_fin)
            self._rnn.step_output(new_ids)
            self._rnn.step_output(parents)
            self._rnn.step_output(new_scores)

    def __call__(self):
        """Backtrack the recorded selections into ranked sequences:
        (translation_ids [B, beam, T], translation_scores [B, beam])."""
        if self._status != BeamSearchDecoder.AFTER_BEAM_SEARCH_DECODER:
            raise ValueError("Output of BeamSearchDecoder object can only "
                             "be visited outside the block.")
        ids_hist, parents_hist, scores_hist = self._rnn()
        final_scores = layers.squeeze(
            layers.slice(scores_hist, axes=[1], starts=[self._max_len - 1],
                         ends=[self._max_len]), axes=[1])
        return beam_backtrack(ids_hist, parents_hist, final_scores)

    def _assert_in_decoder_block(self, method):
        if self._status != BeamSearchDecoder.IN_BEAM_SEARCH_DECODER:
            raise ValueError(f"{method} should be invoked inside block of "
                             "BeamSearchDecoder object.")
