"""`fluid.contrib` namespace (reference python/paddle/fluid/contrib/)."""

from . import decoder  # noqa: F401
from .decoder import InitState, StateCell, TrainingDecoder, BeamSearchDecoder  # noqa: F401

__all__ = ["decoder", "InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]
