"""Sequence-numbered update log with an acknowledged watermark.

The replication contract's loss bound lives here: the primary appends
one record per applied update, the forwarder streams records to the
backup, and the backup's acknowledgement advances `acked_seq`. The
window between `head_seq` and `acked_seq` is the ONLY state a failover
can lose — `append` blocks once `head - acked >= window`, so the bound
is enforced by backpressure, not hoped for (tests pin it by freezing
the forwarder and counting exactly which updates a promoted backup is
missing).

Degradation beats deadlock: when the backup is gone (no ack moves the
watermark for `stall_timeout_s` while the window is full), the log
DEGRADES — recording stops, the ring clears, and `needs_resync` is set
so the forwarder performs a full snapshot sync when the peer returns.
While degraded there is no failover target anyway, so blocking trainer
pushes would trade availability for nothing.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple


class ReplicationStalled(RuntimeError):
    """The in-flight window filled and no ack arrived within the stall
    timeout — the log has degraded to solo mode."""


class UpdateLog:
    def __init__(self, window: int = 512, stall_timeout_s: float = 5.0):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self.stall_timeout_s = float(stall_timeout_s)
        self._cond = threading.Condition()
        # list of (seq, cmd, payload, t_monotonic, trace); seqs are
        # contiguous; trace is the recording request's traceparent (or
        # None) — fluid-horizon links the backup's apply span to it
        self._records: List[Tuple[int, str, dict, float, Optional[str]]] = []  # guarded_by: self._cond
        self._head = 0      # guarded_by: self._cond
        self._acked = 0     # guarded_by: self._cond
        self._degraded = False  # guarded_by: self._cond
        # a fresh pair always starts with a sync
        self._needs_resync = True  # guarded_by: self._cond

    # -- primary write path ----------------------------------------------
    def append(self, cmd: str, payload: dict,
               trace: Optional[str] = None) -> Optional[int]:
        """Record one applied update; returns its seq, or None when the
        log is degraded (the update is applied locally but will only
        reach the backup via the next full resync). Blocks while the
        in-flight window is full — this backpressure IS the loss bound.
        `trace` (a traceparent string) names the request that caused
        the update, so the backup's replay parents under it."""
        deadline = time.monotonic() + self.stall_timeout_s
        with self._cond:
            if self._degraded:
                return None
            while self._head - self._acked >= self.window:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # the backup stopped acking: degrade rather than
                    # wedge the trainers behind a dead replica
                    self._degrade_locked()
                    return None
                self._cond.wait(remaining)
                if self._degraded:
                    return None
            self._head += 1
            self._records.append((self._head, cmd, payload,
                                  time.monotonic(), trace))
            self._cond.notify_all()
            return self._head

    # -- forwarder read path ---------------------------------------------
    def batch(self, max_records: int = 64
              ) -> List[Tuple[int, str, dict, Optional[str]]]:
        """Unacked records in seq order (oldest first), up to
        `max_records`, as (seq, cmd, payload, trace) — the backup's
        replay accepts the legacy 3-tuple shape too, so a mixed-version
        pair keeps streaming. Retransmits everything past the watermark
        — the backup dedups by seq, so a lost ack costs bytes, never
        correctness."""
        with self._cond:
            return [(s, c, p, tr) for s, c, p, _t, tr in
                    self._records[:max_records]]

    def ack(self, seq: int) -> None:
        """The backup applied everything through `seq`: trim and release
        any appender blocked on the window."""
        with self._cond:
            if seq <= self._acked:
                return
            self._acked = min(seq, self._head)
            while self._records and self._records[0][0] <= self._acked:
                self._records.pop(0)
            self._cond.notify_all()

    def wait_pending(self, timeout: Optional[float] = None) -> bool:
        """Block until a record is pending (or degraded/timeout); the
        forwarder's idle sleep, interruptible by the next append."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._records and not self._degraded:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return bool(self._records)

    # -- watermarks / lag --------------------------------------------------
    @property
    def head_seq(self) -> int:
        with self._cond:
            return self._head

    @property
    def acked_seq(self) -> int:
        with self._cond:
            return self._acked

    def lag(self) -> int:
        """Records the backup has NOT caught up on. While a resync is
        pending (`needs_resync`, not degraded) the acked watermark
        cannot express the true backlog — `resume()` advances it at the
        snapshot CUT, before the snapshot lands — so the lag is floored
        at 1 until `rebase()` confirms the install. Without this floor,
        `lag() == 0` (the universal "backup is current" probe: tests,
        the handover drain, the lag gauges) is transiently TRUE during
        the in-flight `haven_sync` RPC of a fresh pair's first full
        sync, a race a loaded box hits for real. A DEGRADED log still
        reports 0: recording is suspended on purpose there (solo
        availability mode), which is idle, not backlog."""
        with self._cond:
            base = self._head - self._acked
            if self._needs_resync and not self._degraded:
                return max(base, 1)
            return base

    def oldest_unacked_age_s(self) -> float:
        with self._cond:
            if not self._records:
                return 0.0
            return max(0.0, time.monotonic() - self._records[0][3])

    # -- degradation / resync ---------------------------------------------
    @property
    def degraded(self) -> bool:
        with self._cond:
            return self._degraded

    @property
    def needs_resync(self) -> bool:
        """Locked read: the replicator loop and the handover drain poll
        this from their own threads."""
        with self._cond:
            return self._needs_resync

    def _degrade_locked(self):
        self._degraded = True
        self._needs_resync = True
        self._records.clear()
        self._acked = self._head
        self._cond.notify_all()

    def degrade(self):
        with self._cond:
            self._degrade_locked()

    def _advance_locked(self, seq: int):
        self._acked = max(self._acked, min(int(seq), self._head))
        while self._records and self._records[0][0] <= self._acked:
            self._records.pop(0)
        self._degraded = False
        self._cond.notify_all()

    def resume(self, seq: int):
        """Called AT a quiesced snapshot cut at `seq`: recording resumes
        immediately (the snapshot contains everything through the cut,
        and no mutator can slip an update between the cut and this call
        while the quiesce is held), while `needs_resync` stays set until
        the snapshot actually lands on the backup. Records appended
        after the cut are KEPT — they must still stream."""
        with self._cond:
            self._advance_locked(seq)

    def rebase(self, seq: Optional[int] = None):
        """The snapshot at `seq` (default: head) landed on the backup:
        advance the watermark past it and clear the resync flag."""
        with self._cond:
            self._advance_locked(self._head if seq is None else seq)
            self._needs_resync = False
