"""fluid-haven: a replicated, self-healing parameter-server plane.

Round 9 (`ark/`) gave pserver training checkpoints, retries, and READ
failover; a pserver death still lost every update since the last
checkpoint serial and wedged training until an operator restarted it.
fluid-haven makes a shard survivable in lease-time with a provable loss
bound — the TF system paper's PS fault-tolerance story, and the layer
the reference repo's etcd-backed Go EDL pserver occupied in the cloud
deployment:

- **write-path replication** (`replication.py`): the primary forwards
  every applied update to a backup as logical update records over the
  existing rpc framing (the trainer's codec-tagged fluid-wire payloads
  travel verbatim, so the backup is bit-identical and the replication
  hop is as compressed as the trainer hop);
- **bounded-async update log** (`log.py`): sequence-numbered records
  with an acknowledged watermark; failover loss is provably <= the
  in-flight window because `append` backpressures when it fills;
- **lease-based failover**: the backup holds the primary's heartbeat
  lease (`ark.LeaseTable`) and promotes itself when it expires, fenced
  by a monotone epoch; `PSClient` re-resolves a shard's primary on
  transport error or redirect and replays un-watermarked pushes through
  the existing dedup, so promotion never double-applies;
- **live shard handoff**: `ParameterServer.handover()` streams a
  consistent snapshot + log tail to a fresh process, flips the lease
  with zero failed trainer pushes, and retires.

See docs/FAULT_TOLERANCE.md §Replicated PS plane for the contract, the
loss-bound pin, and how to read the `ps_replication_*` metrics.
"""

from .log import ReplicationStalled, UpdateLog  # noqa: F401
from .replication import (CONTROL_CMDS, COUNTED_CMDS,  # noqa: F401
                          DISPATCH_RECORDED_CMDS, LAG_UPDATES_METRIC,
                          LAG_US_METRIC, MUTATING_CMDS, PROMOTIONS_METRIC,
                          READ_CMDS, RECORDED_CMDS, STEP_DOWNS_METRIC,
                          SYNC_APPLY_RECORD, SYNC_RESET_RECORD, HavenState,
                          Replicator)
