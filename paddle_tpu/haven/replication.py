"""fluid-haven: primary/backup replication of a pserver shard.

Replication unit — **logical update records**: the primary forwards each
applied mutating command (`push_grad`, `push_grads`, `push_grads_sync`,
`push_sparse_grad`, `init_param`, `init_table`, plus a synthesized
`__sync_apply__` carrying the barrier's contributor count) to its backup
as the ORIGINAL wire payload, and the backup replays it through the
identical handler path. Chosen over the two alternatives the design
space offers:

- *post-optimizer state* would ship state-sized bytes per update
  (params + optimizer accumulators, 2-3x the shard) where a record is
  gradient-sized;
- *re-encoded logical gradients* would quantize a second time — the
  backup would drift from the primary by one extra rounding per update.

Forwarding the trainer's own (possibly codec-tagged, fluid-wire)
payload keeps the replication hop exactly as compressed as the trainer
hop, and because decoding is deterministic the backup is BIT-IDENTICAL
to the primary at every acknowledged seq. The dedup watermarks
((trainer, batch, session) for sync, (trainer, seq, session) for async)
replicate for free — the backup runs the same handler — so a client
replaying un-acknowledged pushes at a promoted backup can never
double-apply. On the barrierless async path, records are logged in
handler-completion order; concurrent multi-tenant pushes may therefore
replay in a different per-param interleaving than the primary applied —
the same commutation error class as async staleness itself, and zero on
the sync path or with a single writer.

Election rides `ark.LeaseTable`: every replication batch (including
idle heartbeats at lease/3) renews the primary's lease ON the backup;
a standby whose primary's lease expires promotes itself. Promotions and
handovers carry a fencing **epoch** — a record stream from a lower
epoch than the receiver's is answered with a redirect naming the real
primary, so a deposed primary steps down instead of split-braining.

Failure model — CRASH-STOP by default, PARTITION-TOLERANT with a
quorum. A bare 2-node pair cannot distinguish "peer died" from "peer
unreachable": the isolated backup promotes on lease expiry while the
primary keeps serving clients that can still reach it, and every
update the deposed primary acknowledges solo is discarded when the
partition heals and the first contact fences it (`haven_fenced`) —
run `start_standby(auto_promote=False)` there. Arming a fluid-quorum
arbiter group (`quorum_endpoints=` on both members) upgrades the
failure model, and `auto_promote=True` becomes the safe default:

- the standby promotes ONLY on a quorum-granted lease (a strict
  majority of arbiters at a fencing epoch above every epoch any
  earlier majority granted), so a replication-link partition alone can
  never split-brain the pair;
- the primary renews its quorum lease at lease/3 and FAILS CLOSED: a
  renew round that cannot reach a majority fences the write path
  (mutators HELD, not acked) immediately, and local lease expiry steps
  the node down to an unsynced standby BEFORE the arbiters would let a
  rival win — at every observable point at most one member accepts
  writes, with margin (arbiter-side expiry trails the holder's local
  expiry);
- a deposed primary's `has_synced` is cleared at step-down: its solo
  tail (updates acked after the partition cut replication — bounded by
  the in-flight window) is divergent history, so healing rejoins it as
  a resyncing standby and nothing the backup acknowledged is ever
  lost.

`tools/chaos_drill.py --scenario ps_partition` proves the claim under
async and sync PS with `ark.chaos.NetPartition`.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import flags as _flags
from ..ark.liveness import LeaseTable
from ..observe import flight as _flight
from ..observe import metrics as _metrics
from ..observe import xray as _xray
from .log import UpdateLog

logger = logging.getLogger(__name__)

#: commands a standby backup must redirect to the primary (role gate)
MUTATING_CMDS = frozenset({
    "init_param", "init_table", "push_grad", "push_grads",
    "push_grads_sync", "push_sparse_grad", "sync_apply", "batch_barrier",
    "heartbeat", "restore",
})

#: the subset COUNTED as in-flight mutators for `quiesce()` — only the
#: handlers that mutate shard state for their whole duration. The
#: blocking barrier commands are deliberately NOT here: a sync_apply
#: parked in the barrier would hold quiesce for sync_timeout while the
#: held pushes starve the barrier (and held heartbeats would get
#: healthy trainers evicted). Their actual state mutation runs in
#: `_apply_pending`, which enters the gate via `mutator()` itself.
COUNTED_CMDS = frozenset({
    "init_param", "init_table", "push_grad", "push_grads",
    "push_grads_sync", "push_sparse_grad", "restore",
})

#: the subset that is replicated as update records (sync_apply is
#: replicated from inside the barrier action instead — one synthesized
#: record per batch, carrying the contributor count; restore triggers a
#: full resync; barriers and trainer heartbeats are primary-local)
RECORDED_CMDS = frozenset({
    "init_param", "init_table", "push_grad", "push_grads",
    "push_grads_sync", "push_sparse_grad",
})

#: the subset the DISPATCH wrapper records after a successful reply.
#: push_grads_sync records itself inside the pending lock instead — the
#: log order must equal the accumulation order, or concurrent trainers'
#: pending sums would fold in a different order on the backup (float
#: non-associativity would break sync-path bit-identity)
DISPATCH_RECORDED_CMDS = RECORDED_CMDS - frozenset({"push_grads_sync"})

#: read-side commands a standby backup serves (bounded-stale by the
#: replication window) — this is what keeps fluid-fleet's serve-time
#: sparse row pulls alive through a primary kill, no promotion needed
READ_CMDS = frozenset({"get_param", "get_params", "prefetch"})

#: commands every role answers (control/introspection plane)
CONTROL_CMDS = frozenset({"stats", "wire_caps", "haven_role",
                          "haven_replicate", "haven_sync", "haven_promote",
                          "save", "stop"})

#: the synthesized record replaying a sync barrier's exactly-once apply
SYNC_APPLY_RECORD = "__sync_apply__"

#: the synthesized record replaying a broken-barrier recovery: the
#: primary discarded its incomplete pending batch — the backup must
#: discard too, or the retried batch's pushes would dedup against the
#: stale pending set and the two copies would diverge
SYNC_RESET_RECORD = "__sync_reset__"

LAG_UPDATES_METRIC = "ps_replication_lag_updates"
LAG_US_METRIC = "ps_replication_lag_us"
PROMOTIONS_METRIC = "ps_promotions_total"
STEP_DOWNS_METRIC = "ps_step_downs_total"


class HavenState:
    """Per-server replication state: role, fencing epoch, the update
    log (primary) or applied watermark (backup), the serve gate, and
    the promotion machinery. Attached to a `ParameterServer` as
    `server._haven` by `start_replication()` / `start_standby()`."""

    def __init__(self, server, role: str = "primary",
                 lease_s: float = 2.0, window: int = 512,
                 stall_timeout_s: float = 5.0):
        self.server = server
        self.role = role                 # primary | backup | retired
        self.epoch = 0
        self.lease_s = float(lease_s)
        self.peer: Optional[str] = None          # primary -> its backup
        self.primary_ep: Optional[str] = None    # backup -> its primary
        self.redirect_to: Optional[str] = None   # retired -> successor
        self.auto_promote = True
        self.log = UpdateLog(window=window, stall_timeout_s=stall_timeout_s)
        self.applied_seq = 0             # backup-side replay watermark
        self.has_synced = False
        self.primary_lease = LeaseTable()
        self._state_lock = threading.RLock()
        self._replay_lock = threading.Lock()
        # serve gate: counts in-flight mutators; `quiesce` holds new ones
        self._gate = threading.Condition()
        self._active = 0  # guarded_by: self._gate
        self._held = False  # guarded_by: self._gate
        self._replicator: Optional[Replicator] = None
        self._monitor: Optional[threading.Thread] = None
        # fluid-quorum (arm_quorum): the arbiter client, the shard's
        # lease resource, the held lease + its renewal thread, and the
        # fail-closed fence (mutators held while a renew round cannot
        # reach a majority)
        self.quorum = None
        self.resource: Optional[str] = None
        self.quorum_lease_s: Optional[float] = None
        self._qlease = None  # guarded_by: self._state_lock
        self._renewer: Optional[threading.Thread] = None
        self._fenced = False  # guarded_by: self._gate
        self._stop = threading.Event()
        # test hook: raise at a named handover cut point ("pre_promote" /
        # "post_promote") to drill the torn-handoff contract
        self._handover_fault: Optional[str] = None

    # -- serve gate --------------------------------------------------------
    def _verdict(self, cmd: str):
        """None = serve it; otherwise the redirect reply."""
        role = self.role
        if role == "primary" or cmd in CONTROL_CMDS:
            return None
        if role == "backup":
            if cmd in READ_CMDS:
                return None
            return ("redirect", {"primary": self.primary_ep,
                                 "epoch": self.epoch})
        # retired: even reads redirect — a frozen shard must not serve
        # stale params to a trainer that missed the flip
        return ("redirect", {"primary": self.redirect_to or self.primary_ep,
                             "epoch": self.epoch})

    @contextlib.contextmanager
    def admit(self, cmd: str):
        """Dispatch-time gate: yields None to serve, or the redirect
        reply. State-mutating commands are counted in-flight (and held
        while a quiesce is cutting) so snapshots/handovers see a stable
        state; barrier waits and heartbeats pass uncounted (see
        COUNTED_CMDS)."""
        entered = False
        with self._gate:
            # _fenced: a quorum-armed primary whose renew round failed
            # holds (not fails) mutators — a transient blip resumes
            # them, a real deposition flips the role and the redirect
            # verdict below releases them toward the new primary
            while (self._held or self._fenced) and cmd in COUNTED_CMDS:
                self._gate.wait(timeout=1.0)
            verdict = self._verdict(cmd)
            if verdict is None and cmd in COUNTED_CMDS:
                self._active += 1
                entered = True
        try:
            yield verdict
        finally:
            if entered:
                with self._gate:
                    self._active -= 1
                    self._gate.notify_all()

    @contextlib.contextmanager
    def mutator(self):
        """Out-of-dispatch state mutation (the sync barrier's
        `_apply_pending`, backup-side record replay/snapshot install):
        same held/counted contract as a COUNTED command, so a quiesced
        cut never observes it mid-write."""
        with self._gate:
            while self._held or self._fenced:
                self._gate.wait(timeout=1.0)
            self._active += 1
        try:
            yield
        finally:
            with self._gate:
                self._active -= 1
                self._gate.notify_all()

    @contextlib.contextmanager
    def quiesce(self):
        """Block new mutators and wait out in-flight ones: inside the
        context the shard state is a consistent cut at `log.head_seq`
        (the watermark a checkpoint or snapshot is tagged with)."""
        with self._gate:
            while self._held:
                self._gate.wait()
            self._held = True
            while self._active:
                self._gate.wait(timeout=0.5)
        try:
            yield
        finally:
            with self._gate:
                self._held = False
                self._gate.notify_all()

    # -- primary: recording ------------------------------------------------
    def record(self, cmd: str, payload: dict) -> None:
        """Append one applied update to the log (primary role only).
        A degraded log (backup gone past the stall timeout) drops the
        record and flags the pair for a full resync — availability over
        replication once there is no failover target left."""
        # local snapshot: a concurrent step-down/demotion may null the
        # forwarder between the check and the kick (kicking a stopped
        # forwarder is a harmless event set)
        rep = self._replicator
        if self.role != "primary" or rep is None:
            return
        trace = None
        if _flags.get_flag("observe"):
            # fluid-horizon: remember WHICH request produced this update
            # (the rpc_server:* span active in the dispatching handler),
            # so the backup's replay span joins the trainer's trace
            # across the replication stream
            ctx = _xray.current()
            if ctx is not None:
                trace = _xray.to_traceparent(ctx)
        was = self.log.degraded
        if self.log.append(cmd, payload, trace=trace) is None and not was:
            _flight.note("haven_degraded", endpoint=self.server.endpoint,
                         head_seq=self.log.head_seq)
            logger.warning("haven %s: replication degraded (backup %s "
                           "unresponsive) — recording suspended until "
                           "resync", self.server.endpoint, self.peer)
        rep.kick()

    def record_sync_apply(self, n_contrib: int) -> None:
        """Called from inside `_apply_pending` (under the pending lock)
        so the apply record orders exactly between the batch's pushes
        and the next batch's."""
        self.record(SYNC_APPLY_RECORD, {"n_contrib": int(n_contrib)})

    def mark_resync(self) -> None:
        """State changed out-of-band (a restore): the log can no longer
        bring the backup up to date — force a full snapshot sync."""
        self.log.degrade()
        rep = self._replicator
        if rep is not None:
            rep.kick()

    # -- quorum (fluid-quorum) ---------------------------------------------
    def arm_quorum(self, client, resource: str,
                   lease_s: Optional[float] = None) -> "HavenState":
        """Attach a fluid-quorum arbiter group: elections for this shard
        now require a majority-granted lease on `resource`, and this
        node fails closed when it cannot renew. Both members of a pair
        must name the SAME resource. No quorum armed = the exact PR 12
        crash-stop behavior, bit for bit."""
        self.quorum = client
        self.resource = str(resource)
        self.quorum_lease_s = float(lease_s) if lease_s else self.lease_s
        return self

    def _quorum_acquire(self, kind: str) -> Optional[int]:
        """Campaign for the shard lease; returns the fencing epoch on a
        majority grant (and arms the renewal loop), None when the
        election is lost. Raises QuorumUnavailable when no arbiter
        answered at all."""
        lease = self.quorum.campaign(self.resource, self.server.endpoint,
                                     self.quorum_lease_s)
        if lease is None:
            return None
        with self._state_lock:
            self._qlease = lease
        self._set_fenced(False)
        self._ensure_renewer()
        _flight.note("quorum_lease_acquired",
                     endpoint=self.server.endpoint,
                     resource=self.resource, epoch=lease.epoch, via=kind)
        return lease.epoch

    def _set_fenced(self, fenced: bool, reason: str = "") -> None:
        with self._gate:
            if self._fenced == fenced:
                return
            self._fenced = fenced
            self._gate.notify_all()
        if fenced:
            logger.warning("haven %s: FENCED (%s) — mutators held until "
                           "the quorum lease renews or expires",
                           self.server.endpoint, reason)
            _flight.note("haven_fence", endpoint=self.server.endpoint,
                         reason=reason)
        else:
            _flight.note("haven_unfence", endpoint=self.server.endpoint)

    def _ensure_renewer(self) -> None:
        if self._renewer is None or not self._renewer.is_alive():
            self._renewer = threading.Thread(
                target=self._renew_loop, daemon=True,
                name=f"quorum-renew@{self.server.endpoint}")
            self._renewer.start()

    def _renew_loop(self) -> None:
        """Lease renewal at lease/3. The loop follows the LEASE, not
        the role (a just-elected standby holds its grant for a moment
        before promote() flips the role — exiting on role would leave
        the new primary's lease to silently expire); it ends when the
        lease is dropped (step-down, demotion, handover, resign).

        Fail closed on the primary: the FIRST renew round that cannot
        reach a majority fences the write path; recovery before local
        expiry unfences; local expiry steps the node down (the
        arbiters' own expiry — which started later — is what then lets
        a rival win, so the fence always precedes the rival's grant)."""
        while not self._stop.is_set():
            with self._state_lock:
                lease = self._qlease
            if lease is None:
                return
            interval = max(lease.lease_s / 3.0, 0.05)
            if self._stop.wait(interval):
                return
            with self._state_lock:
                lease = self._qlease
            if lease is None:
                return
            try:
                ok = self.quorum.renew(lease)
            except Exception:   # noqa: BLE001 — unreachable == failed
                ok = False
            if ok:
                with self._gate:
                    fenced = self._fenced
                if fenced:
                    self._set_fenced(False)
                continue
            if self.role == "primary":
                self._set_fenced(True, reason="quorum renew failed")
                if not lease.live:
                    self._quorum_step_down("lease_expired")
                    return
            elif not lease.live:
                # a non-primary holder (the adopt->promote window never
                # closed, e.g. promote() raised): drop the dead lease
                with self._state_lock:
                    if self._qlease is lease:
                        self._qlease = None
                return

    def _quorum_step_down(self, reason: str) -> None:
        """Deposed (or presumed deposed): stop accepting writes for
        good, become an UNSYNCED standby — `has_synced` is cleared
        because any update acknowledged solo since the last backup ack
        is divergent history; the new primary's first contact performs
        a full resync (the healed-partition rejoin contract)."""
        with self._state_lock:
            if self.role != "primary":
                return
            self.role = "backup"
            self.primary_ep = None   # learned from the winner's sync
            self.has_synced = False
            self._qlease = None
        logger.warning("haven %s: STEPPED DOWN (%s) — resyncing standby",
                       self.server.endpoint, reason)
        _flight.note("haven_step_down", endpoint=self.server.endpoint,
                     reason=reason)
        _metrics.counter(
            STEP_DOWNS_METRIC,
            "quorum-armed primaries that stepped down").inc(reason=reason)
        self._set_fenced(False)
        self._stop_replicator()
        self._ensure_monitor()

    # -- backup: replay ----------------------------------------------------
    def replay(self, records: List[Tuple[int, str, dict]], epoch: int,
               primary: str, lease_s: float):
        """`haven_replicate` body: fence by epoch, renew the primary's
        lease, apply in-order records past the watermark (seq dedup
        makes retransmits free), ack the new watermark."""
        with self._state_lock:
            if epoch < self.epoch:
                return ("redirect", {"primary": self.current_primary(),
                                     "epoch": self.epoch})
            if self.role == "primary":
                if epoch <= self.epoch:
                    # a deposed primary still streaming at our epoch:
                    # tell it who rules now
                    return ("redirect",
                            {"primary": self.server.endpoint,
                             "epoch": self.epoch})
                self._demote(primary, epoch)
            self.epoch = max(self.epoch, int(epoch))
            self.primary_ep = primary
        self.primary_lease.beat("primary", lease_s=float(lease_s))
        if not self.has_synced:
            # never apply records onto a shard that missed its snapshot
            return ("ok", {"acked": self.applied_seq, "epoch": self.epoch,
                           "need_resync": True})
        need_resync = False
        obs = _flags.get_flag("observe")
        with self._replay_lock, self.mutator():
            # mutator(): a backup-side save/snapshot quiesce must not
            # observe a half-replayed record
            for seq, cmd, payload, *rest in records:
                if seq <= self.applied_seq:
                    continue
                if seq != self.applied_seq + 1:
                    need_resync = True
                    break
                # fluid-horizon: a 4-tuple record carries the causing
                # request's traceparent — the apply span closes the
                # trainer -> primary -> backup chain (3-tuples from a
                # legacy primary replay untraced)
                rctx = _xray.parse_traceparent(rest[0]) \
                    if obs and rest else None
                if rctx is not None:
                    with _xray.activate(rctx), \
                            _xray.span(f"haven_apply:{cmd}", cat="ha",
                                       seq=seq, cmd=cmd):
                        self._apply_record(cmd, payload)
                else:
                    self._apply_record(cmd, payload)
                self.applied_seq = seq
        reply = {"acked": self.applied_seq, "epoch": self.epoch}
        if need_resync or not self.has_synced:
            reply["need_resync"] = True
        return ("ok", reply)

    def _apply_record(self, cmd: str, payload: dict) -> None:
        srv = self.server
        if cmd == SYNC_APPLY_RECORD:
            srv._apply_pending(n_contrib=payload["n_contrib"],
                               replicated=True)
            return
        if cmd == SYNC_RESET_RECORD:
            with srv._pending_lock:
                srv._pending.clear()
                srv._sync_pending_from.clear()
            return
        handler = getattr(srv, f"_h_{cmd}")
        handler(**payload)

    def _demote(self, primary: str, epoch: int) -> None:
        # a higher-epoch primary exists (handover flipped the crown
        # while we thought we ruled): step back down to standby — and
        # re-arm the promotion monitor, or this node could never take
        # over again when its NEW primary dies
        logger.warning("haven %s: demoted by primary %s (epoch %d > %d)",
                       self.server.endpoint, primary, epoch, self.epoch)
        _flight.note("haven_demotion", endpoint=self.server.endpoint,
                     new_primary=primary, epoch=epoch)
        self.role = "backup"
        self._qlease = None   # the rival's higher epoch fenced our lease
        self._stop_replicator()
        self._set_fenced(False)
        self._ensure_monitor()

    # -- snapshots ---------------------------------------------------------
    def snapshot(self) -> dict:
        """Full shard state at the current watermark. Caller holds
        `quiesce()` (or knows the server is idle) so the cut is
        consistent."""
        srv = self.server
        dense = {n: v.copy() for n, v in srv._dense.items()}
        sparse = {n: t.value.copy() for n, t in srv._sparse.items()}
        optim = {}
        for n, opt in srv._optim.items():
            opt_type, _lr, _attrs = srv._opt_cfg[n]
            st = opt.state()
            optim[n] = {"opt_type": opt_type, "lr": st["lr"],
                        "attrs": dict(st["attrs"]),
                        "acc": {k: np.array(a, copy=True)
                                for k, a in st["acc"].items()}}
        with srv._pending_lock:
            sync = {"applied": dict(srv._sync_applied),
                    "sessions": dict(srv._sync_sessions),
                    "pending_from": sorted(srv._sync_pending_from),
                    "pending": {n: g.copy()
                                for n, g in srv._pending.items()}}
        with srv._async_lock:
            marks = {"applied": dict(srv._async_applied),
                     "sessions": dict(srv._async_sessions)}
        return {"seq": self.log.head_seq, "epoch": self.epoch,
                "dense": dense, "sparse": sparse, "optim": optim,
                "sync": sync, "async_marks": marks,
                "primary": self.server.endpoint}

    def install_snapshot(self, snap: dict, lease_s: Optional[float] = None):
        """`haven_sync` body: replace the whole shard state with the
        primary's consistent cut and align the replay watermark."""
        from ..pserver.optim import make_optimizer
        from ..pserver.server import _SparseTable

        with self._state_lock:
            if snap["epoch"] < self.epoch:
                return ("redirect", {"primary": self.current_primary(),
                                     "epoch": self.epoch})
            if self.role == "primary":
                if snap["epoch"] <= self.epoch:
                    return ("redirect",
                            {"primary": self.server.endpoint,
                             "epoch": self.epoch})
                # a legitimately higher-epoch primary syncing us (the
                # same demotion rule replay() applies — and sync is the
                # path a fresh successor's forwarder always runs FIRST)
                self._demote(snap.get("primary"), int(snap["epoch"]))
            self.epoch = max(self.epoch, int(snap["epoch"]))
            self.primary_ep = snap.get("primary")
        srv = self.server
        with self._replay_lock, self.mutator():
            srv._dense = {n: np.array(v, copy=True)
                          for n, v in snap["dense"].items()}
            sparse = {}
            for n, v in snap["sparse"].items():
                t = _SparseTable.__new__(_SparseTable)
                t.value = np.array(v, copy=True)
                sparse[n] = t
            srv._sparse = sparse
            optim, cfg = {}, {}
            for n, rec in snap["optim"].items():
                opt = make_optimizer(rec["opt_type"], rec["lr"],
                                     rec["attrs"])
                opt.load_state({"lr": rec["lr"], "attrs": rec["attrs"],
                                "acc": {k: np.array(a, copy=True)
                                        for k, a in rec["acc"].items()}})
                optim[n] = opt
                cfg[n] = (rec["opt_type"], float(rec["lr"]),
                          dict(rec["attrs"]))
            srv._optim = optim
            srv._opt_cfg = cfg
            with srv._pending_lock:
                srv._sync_applied = dict(snap["sync"]["applied"])
                srv._sync_sessions = dict(snap["sync"]["sessions"])
                srv._sync_pending_from = {tuple(x) for x in
                                          snap["sync"]["pending_from"]}
                srv._pending = {n: np.array(g, copy=True)
                                for n, g in snap["sync"]["pending"].items()}
            with srv._async_lock:
                srv._async_applied = dict(snap["async_marks"]["applied"])
                srv._async_sessions = dict(snap["async_marks"]["sessions"])
            self.applied_seq = int(snap["seq"])
            self.has_synced = True
        self.primary_lease.beat("primary",
                                lease_s=float(lease_s or self.lease_s))
        _flight.note("haven_synced", endpoint=srv.endpoint,
                     seq=self.applied_seq, epoch=self.epoch)
        return ("ok", {"acked": self.applied_seq, "epoch": self.epoch})

    # -- promotion ---------------------------------------------------------
    def promote(self, kind: str = "lease_expiry", epoch: Optional[int] = None,
                backup: Optional[str] = None,
                predecessor: Optional[str] = None) -> bool:
        """Standby -> primary. `kind` is "lease_expiry" (self-election on
        a dead primary), "quorum" (the monitor won a majority-granted
        lease), or "handover" (the `predecessor` handed us the crown,
        with `epoch` fenced one above its own and optionally the
        surviving `backup` to replicate to)."""
        if self.quorum is not None:
            with self._state_lock:
                have = self._qlease is not None and self._qlease.live
            if not have:
                # every road to primary goes through the arbiters: a
                # handover target (the predecessor resigned first) and
                # an operator promote() both campaign here; a monitor
                # election arrives with the lease already adopted
                won = self._quorum_acquire(kind)
                if won is None:
                    raise RuntimeError(
                        f"promote({kind}): quorum election lost for "
                        f"{self.resource!r} — a rival holds the lease "
                        f"or this side has no majority")
                epoch = max(int(epoch or 0), won)
        with self._state_lock:
            if self.role == "primary":
                return False
            predecessor = predecessor or self.primary_ep
            self.role = "primary"
            self.epoch = int(epoch) if epoch is not None else self.epoch + 1
            self.redirect_to = None
            new_epoch = self.epoch
        logger.warning("haven %s: PROMOTED to primary (epoch %d, %s, "
                       "succeeding %s)", self.server.endpoint, new_epoch,
                       kind, predecessor)
        # the promotion event goes to the black box unconditionally —
        # it is exactly what a postmortem on the survivor wants (the
        # predecessor names WHOSE death/handover this was)
        _flight.note("haven_promotion", endpoint=self.server.endpoint,
                     epoch=new_epoch, promotion=kind,
                     predecessor=predecessor,
                     applied_seq=self.applied_seq)
        _metrics.counter(
            PROMOTIONS_METRIC,
            "backup shards promoted to primary").inc(kind=kind)
        if _flags.get_flag("observe"):
            _metrics.gauge(LAG_UPDATES_METRIC,
                           "update-log records not yet acknowledged by "
                           "the backup").set(0.0)
        if backup:
            self.start_replication(backup)
        return True

    def _monitor_loop(self):
        from ..quorum import QuorumUnavailable

        poll = max(self.lease_s / 3.0, 0.05)
        while not self._stop.wait(poll):
            if self.role != "backup" or not self.auto_promote \
                    or not self.has_synced:
                continue
            if "primary" not in self.primary_lease.expired():
                continue
            if self.quorum is None:
                self.promote(kind="lease_expiry")
                return
            # quorum-gated election: promote ONLY on a majority grant.
            # A rejection ("held": the primary is alive to a majority —
            # only OUR link to it is down; or no majority: WE are the
            # minority side) fails closed and keeps polling — the
            # split-brain the crash-stop model could not exclude.
            old_primary = self.primary_ep
            try:
                won = self._quorum_acquire("lease_expiry")
            except QuorumUnavailable:
                continue
            if won is None:
                continue
            # adopt the deposed primary as OUR backup: when the
            # partition heals, the forwarder's first contact resyncs it
            # (its has_synced was cleared at step-down)
            self.promote(kind="quorum", epoch=won, backup=old_primary)
            return

    def _ensure_monitor(self):
        """(Re)arm the promotion monitor: the loop exits after a
        promotion, so a node demoted back to standby needs a fresh
        thread or it could never self-elect again."""
        if self._monitor is None or not self._monitor.is_alive():
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name=f"haven-monitor@{self.server.endpoint}")
            self._monitor.start()

    # -- wiring ------------------------------------------------------------
    def start_standby(self, auto_promote: bool = True) -> "HavenState":
        self.role = "backup"
        self.auto_promote = bool(auto_promote)
        self._ensure_monitor()
        return self

    def start_replication(self, backup_endpoint: str) -> "HavenState":
        if self.quorum is not None:
            with self._state_lock:
                have = self._qlease is not None and self._qlease.live
            if not have:
                won = self._quorum_acquire("bootstrap")
                if won is None:
                    raise RuntimeError(
                        f"start_replication: quorum election lost for "
                        f"{self.resource!r} — another primary holds the "
                        f"lease (resign it or wait out its expiry)")
                with self._state_lock:
                    self.epoch = max(self.epoch, won)
        self.role = "primary"
        self.peer = backup_endpoint
        self._stop_replicator()
        self._replicator = Replicator(self, backup_endpoint).start()
        return self

    def _stop_replicator(self):
        rep, self._replicator = self._replicator, None
        if rep is not None:
            rep.stop()

    def current_primary(self) -> Optional[str]:
        if self.role == "primary":
            return self.server.endpoint
        return self.redirect_to or self.primary_ep

    def status(self) -> dict:
        with self._gate:
            # the observable lease-holder property: a primary whose gate
            # is HELD (mid-handover quiesce) or FENCED (quorum renew
            # failing) cannot acknowledge a write — at most one member
            # of a group is ever `accepting`
            accepting = self.role == "primary" and not self._held \
                and not self._fenced
            fenced = self._fenced
        out = {"role": self.role, "epoch": self.epoch,
               "endpoint": self.server.endpoint,
               "primary": self.current_primary(),
               "peer": self.peer,
               "accepting": accepting,
               "fenced": fenced,
               "head_seq": self.log.head_seq,
               "acked_seq": self.log.acked_seq,
               "applied_seq": self.applied_seq,
               "lag": self.log.lag(),
               "degraded": self.log.degraded}
        if self.quorum is not None:
            with self._state_lock:
                ql = self._qlease
            out["quorum"] = {"resource": self.resource,
                            "lease_epoch": ql.epoch if ql else 0,
                            "lease_live": bool(ql and ql.live)}
        return out

    # -- handover ----------------------------------------------------------
    def handover(self, new_endpoint: str, timeout: float = 30.0) -> dict:
        """Planned live migration of this primary shard to a fresh
        process at `new_endpoint` (already started, standing by with
        `start_standby(auto_promote=False)`):

        1. quiesce — in-flight mutators drain, new ones are HELD (not
           failed), so no trainer push dies across the flip;
        2. drain — the existing backup acks through the head seq
           (no acknowledged update can be lost by the flip);
        3. sync — full snapshot to the fresh process;
        4. flip — `haven_promote` hands it epoch+1 (and the surviving
           backup to replicate to); exactly one lease-holder exists at
           every observable point because the old primary holds its
           gate until the promote is acknowledged;
        5. retire — this server answers everything with a redirect to
           the successor and stops forwarding.

        A crash before step 4 leaves the OLD pair authoritative (the
        fresh standby never promotes — `auto_promote=False`); a crash
        after it leaves the successor authoritative (higher epoch).
        Either way exactly one shard accepts writes."""
        from ..pserver.client import PSClient

        if self.role != "primary":
            raise RuntimeError(f"handover: role is {self.role!r}, only a "
                               f"primary can hand over its shard")
        t0 = time.monotonic()
        old_backup = self.peer
        client = PSClient([new_endpoint])
        try:
            with self.quiesce():
                # 2. drain the existing backup through head (bounded)
                rep = self._replicator
                if rep is not None:
                    rep.kick()
                    # a needs_resync pair skips the drain: the old
                    # backup is being replaced wholesale by the
                    # successor's full snapshot anyway, and the
                    # forwarder's own resync would block on THIS
                    # quiesce (lag now honestly reports >=1 while a
                    # resync is pending)
                    while self.log.lag() > 0 and not self.log.degraded \
                            and not self.log.needs_resync:
                        if time.monotonic() - t0 > timeout:
                            raise RuntimeError(
                                "handover: backup failed to drain the "
                                "update log in time")
                        time.sleep(0.01)
                snap = self.snapshot()
                snap["epoch"] = self.epoch   # successor fences at +1
                if self._handover_fault == "pre_promote":
                    raise RuntimeError("haven test fault: pre_promote")
                client._call(new_endpoint, "haven_sync", snapshot=snap,
                             lease_s=self.lease_s)
                if self.quorum is not None:
                    # hand the arbiters over too, under the still-held
                    # gate: resign so the successor's campaign (inside
                    # its haven_promote) is not rejected as "held". A
                    # crash between resign and promote self-heals — the
                    # next renew round re-asserts this node's lease at
                    # its persisted epoch (the restart-renew rule).
                    with self._state_lock:
                        ql, self._qlease = self._qlease, None
                    if ql is not None:
                        self.quorum.resign(ql)
                try:
                    reply = client._call(
                        new_endpoint, "haven_promote",
                        epoch=self.epoch + 1, backup=old_backup,
                        predecessor=self.server.endpoint)
                except BaseException:
                    if self.quorum is not None:
                        # the successor never took the crown but we
                        # already resigned: re-campaign NOW (our
                        # persisted epoch makes us the favorite) or
                        # fail closed — a primary without a quorum
                        # lease must not keep accepting writes
                        won = None
                        try:
                            won = self._quorum_acquire("handover_abort")
                        except Exception:   # noqa: BLE001
                            pass
                        if won is None:
                            self._quorum_step_down("handover_abort")
                        else:
                            with self._state_lock:
                                self.epoch = max(self.epoch, won)
                    raise
                # 5. retire IMMEDIATELY after the promote ack, under the
                # still-held gate — no statement may intervene, so there
                # is no instant where both this server and the successor
                # would accept writes (the first mutator released after
                # the gate sees the redirect)
                with self._state_lock:
                    self.role = "retired"
                    self.redirect_to = new_endpoint
                    self.epoch = int(reply.get("epoch", self.epoch + 1))
                if self._handover_fault == "post_promote":
                    raise RuntimeError("haven test fault: post_promote")
                self._stop_replicator()
            _flight.note("haven_handover", endpoint=self.server.endpoint,
                         successor=new_endpoint, epoch=self.epoch,
                         seq=snap["seq"],
                         wall_s=round(time.monotonic() - t0, 3))
            return {"successor": new_endpoint, "epoch": self.epoch,
                    "seq": snap["seq"]}
        finally:
            client.close()

    def close(self):
        self._stop.set()
        self._stop_replicator()
        if self._monitor is not None:
            self._monitor.join(timeout=2.0)
            self._monitor = None
        if self._renewer is not None:
            self._renewer.join(timeout=2.0)
            self._renewer = None
        # NOTE: close() is also the SIGKILL analog (server.stop() calls
        # it), so the held quorum lease is deliberately NOT resigned —
        # a killed primary's lease must expire at the arbiters, exactly
        # the window the failover budget prices in. Planned exits hand
        # over or resign explicitly.
        if self.quorum is not None:
            try:
                self.quorum.close()
            except Exception:   # noqa: BLE001
                pass


class Replicator:
    """The primary-side forwarder: one daemon thread streaming update
    records to the backup over the normal rpc framing, renewing the
    primary's lease on the backup every batch (idle batches are the
    heartbeat), feeding the lag gauges from the ack watermark, and
    performing full snapshot syncs when the pair needs one."""

    MAX_RECORDS = 64

    def __init__(self, haven: HavenState, backup_endpoint: str):
        self.haven = haven
        self.backup = backup_endpoint
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._client = None

    def start(self) -> "Replicator":
        from ..ark.retry import RetryPolicy
        from ..pserver.client import PSClient

        self._client = PSClient(
            [self.backup],
            retry=RetryPolicy(max_attempts=2, base_delay=0.02,
                              max_delay=0.2),
            deadline=max(self.haven.lease_s, 2.0))
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"haven-fwd@{self.haven.server.endpoint}")
        self._thread.start()
        return self

    def kick(self):
        self._kick.set()

    def _gauges(self):
        if not _flags.get_flag("observe"):
            return
        log = self.haven.log
        _metrics.gauge(
            LAG_UPDATES_METRIC,
            "update-log records not yet acknowledged by the backup"
        ).set(float(log.lag()))
        _metrics.gauge(
            LAG_US_METRIC,
            "age of the oldest unacknowledged update record"
        ).set(round(log.oldest_unacked_age_s() * 1e6, 1))

    def _full_sync(self) -> bool:
        hv = self.haven
        # cheap reachability probe BEFORE the expensive quiesced
        # deep-copy: while the backup is down, the degraded loop must
        # not stall every trainer mutator and snapshot the whole shard
        # once per backoff just to fail the connect
        self._client._call(self.backup, "haven_role",
                           _deadline=max(hv.lease_s, 2.0))
        with hv.quiesce():
            snap = hv.snapshot()
            # recording resumes AT the cut, inside the quiesce: an
            # update applied after the cut but before the snapshot lands
            # must be a log record, or it would be lost to the backup
            hv.log.resume(snap["seq"])
        reply = self._client._call(self.backup, "haven_sync",
                                   snapshot=snap, lease_s=hv.lease_s)
        hv.log.rebase(snap["seq"])
        _flight.note("haven_resync", endpoint=hv.server.endpoint,
                     backup=self.backup, seq=snap["seq"])
        logger.info("haven %s: full sync -> %s at seq %d",
                    hv.server.endpoint, self.backup, snap["seq"])
        return bool(reply)

    def _loop(self):
        hv = self.haven
        beat = max(hv.lease_s / 3.0, 0.05)
        backoff = 0.05
        while not self._stop.is_set():
            try:
                if hv.log.needs_resync:
                    self._full_sync()
                self._kick.clear()
                if not hv.log.wait_pending(timeout=beat):
                    if self._stop.is_set():
                        return
                records = hv.log.batch(self.MAX_RECORDS)
                reply = self._client._call(
                    self.backup, "haven_replicate", records=records,
                    epoch=hv.epoch, primary=hv.server.endpoint,
                    lease_s=hv.lease_s)
                if reply.get("need_resync"):
                    hv.log.degrade()
                    self._gauges()
                    continue
                hv.log.ack(int(reply["acked"]))
                self._gauges()
                backoff = 0.05
            except RuntimeError as e:
                if self._stop.is_set():
                    return
                if "NotPrimary" in str(e) or "redirect" in str(e):
                    # fenced by a higher epoch (the backup promoted, or
                    # a handover flipped) — step down, don't split-brain
                    logger.warning("haven %s: fenced by %s (%s) — "
                                   "retiring", hv.server.endpoint,
                                   self.backup, e)
                    with hv._state_lock:
                        if hv.role == "primary":
                            hv.role = "retired"
                            hv.redirect_to = self.backup
                    _flight.note("haven_fenced",
                                 endpoint=hv.server.endpoint,
                                 by=self.backup)
                    return
                # any other err reply is a backup-side fault, not a
                # fencing verdict: log, back off, keep the pair alive
                logger.warning("haven %s: replicate error from %s: %s",
                               hv.server.endpoint, self.backup, e)
                self._kick.wait(timeout=backoff)
                backoff = min(backoff * 2.0, max(beat, 0.5))
            except (ConnectionError, EOFError, OSError):
                if self._stop.is_set():
                    return
                # transport trouble: keep trying — the window's
                # backpressure (then degradation) bounds the exposure.
                # The lag gauges must keep moving HERE too: a silent
                # backup with light push traffic (window never fills)
                # is exactly what the ps_replication_stall detector
                # watches, and a stale gauge feeds its series nothing
                self._gauges()
                self._kick.wait(timeout=backoff)
                backoff = min(backoff * 2.0, max(beat, 0.5))

    def stop(self):
        self._stop.set()
        self._kick.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._client is not None:
            try:
                self._client.close()
            except Exception:
                pass
            self._client = None
