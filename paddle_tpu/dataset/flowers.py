"""Oxford-102 flowers classification readers (reference:
python/paddle/dataset/flowers.py). Samples: (image f32 [3,224,224], label
int in [0,102)). Synthetic fallback: class-colored blobs at the reference
resolution so input pipelines and models see the real shapes."""

from __future__ import annotations

import numpy as np

N_CLASSES = 102
SIZE = 224


def _reader(n_samples, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_samples):
            label = int(rng.randint(0, N_CLASSES))
            img = rng.rand(3, SIZE, SIZE).astype(np.float32) * 0.1
            # class signature: channel means keyed by the label
            img[0] += (label % 7) / 7.0
            img[1] += (label % 11) / 11.0
            img[2] += (label % 13) / 13.0
            yield img, label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader(64, seed=0)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader(16, seed=1)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader(16, seed=2)
