"""Dataset cache/download helpers (reference: python/paddle/dataset/common.py
DATA_HOME + download with md5)."""

from __future__ import annotations

import hashlib
import os
import sys
import urllib.request

DATA_HOME = os.path.expanduser("~/.cache/paddle_tpu/dataset")


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    dirname = os.path.join(DATA_HOME, module_name)
    os.makedirs(dirname, exist_ok=True)
    filename = os.path.join(dirname,
                            save_name or url.split("/")[-1])
    if os.path.exists(filename) and (md5sum is None or md5file(filename) == md5sum):
        return filename
    try:
        urllib.request.urlretrieve(url, filename)
    except Exception as e:
        raise RuntimeError(
            f"cannot download {url} ({e}); this environment may have no "
            f"egress — dataset modules fall back to synthetic data") from e
    if md5sum is not None and md5file(filename) != md5sum:
        raise RuntimeError(f"md5 mismatch for {filename}")
    return filename


def can_download() -> bool:
    return os.environ.get("PADDLE_TPU_ALLOW_DOWNLOAD", "0") == "1"
