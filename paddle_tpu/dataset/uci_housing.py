"""UCI housing regression readers (reference:
python/paddle/dataset/uci_housing.py). Samples: (features[13] f32, [price])."""

from __future__ import annotations

import numpy as np

from . import common

URL = "http://paddlemodels.bj.bcebos.com/uci_housing/housing.data"


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    w = rng.randn(13, 1).astype(np.float32)
    x = rng.randn(n, 13).astype(np.float32)
    y = x @ w + 0.1 * rng.randn(n, 1).astype(np.float32)
    return x, y


def _reader(n, seed):
    def reader():
        x, y = _synthetic(n, seed)
        for xi, yi in zip(x, y):
            yield xi, yi

    return reader


def train():
    return _reader(404, 0)


def test():
    return _reader(102, 1)
