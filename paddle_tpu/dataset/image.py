"""Image preprocessing utilities (reference: python/paddle/dataset/image.py).

The reference shells out to cv2; these are pure-numpy implementations of
the same contracts (HWC uint8/float arrays, CHW conversion for model
feeds), so the data plane has no OpenCV dependency. PIL is used for
decode/resize when available (it is in this image); decode degrades to a
clear error otherwise.
"""

from __future__ import annotations

import tarfile

import numpy as np

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar",
]


def _decode(data_or_path, is_bytes, is_color):
    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "image decode needs PIL (reference used cv2); feed numpy "
            "arrays directly or install pillow") from e
    import io
    src = io.BytesIO(data_or_path) if is_bytes else data_or_path
    with Image.open(src) as im:
        rgb = np.asarray(im.convert("RGB"))
    if not is_color:
        # cv2's grayscale conversion (luminosity weights), reference parity
        g = (0.299 * rgb[..., 0] + 0.587 * rgb[..., 1] + 0.114 * rgb[..., 2])
        return np.clip(np.rint(g), 0, 255).astype(rgb.dtype)
    # the reference decodes with cv2.imread -> BGR channel order; ported
    # pipelines subtract BGR means / feed BGR-trained weights, so match it
    return rgb[..., ::-1]


def load_image_bytes(data, is_color=True):
    """Decode an encoded image byte string to an HWC array in the
    reference's cv2 BGR channel order (reference image.py
    load_image_bytes)."""
    return _decode(data, True, is_color)


def load_image(file, is_color=True):
    """Load an image file to an HWC array in the reference's cv2 BGR
    channel order (reference image.py load_image)."""
    return _decode(file, False, is_color)


def resize_short(im, size):
    """Resize so the SHORTER edge equals `size`, keeping aspect ratio
    (reference image.py resize_short). Nearest-neighbor via numpy."""
    h, w = im.shape[:2]
    if h < w:
        new_h, new_w = size, int(round(w * size / h))
    else:
        new_h, new_w = int(round(h * size / w)), size
    rows = (np.arange(new_h) * h / new_h).astype(np.int64).clip(0, h - 1)
    cols = (np.arange(new_w) * w / new_w).astype(np.int64).clip(0, w - 1)
    return im[rows][:, cols]


def to_chw(im, order=(2, 0, 1)):
    """HWC -> CHW (reference image.py to_chw)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    """Crop the center size x size patch (reference image.py center_crop)."""
    h, w = im.shape[:2]
    h0, w0 = (h - size) // 2, (w - size) // 2
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True):
    """Crop a random size x size patch (reference image.py random_crop)."""
    h, w = im.shape[:2]
    h0 = np.random.randint(0, h - size + 1)
    w0 = np.random.randint(0, w - size + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    """Horizontal mirror (reference image.py left_right_flip)."""
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short -> crop (random+flip when training, center otherwise)
    -> CHW float32 -> optional mean subtraction (reference image.py
    simple_transform)."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size)
        if np.random.randint(2) == 0:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    """load_image + simple_transform (reference image.py
    load_and_transform)."""
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file, dataset_name, img2label,
                          num_per_batch=1024):
    """Decode a tar of images into pickled (data, label) batch files
    (reference image.py batch_images_from_tar); returns the meta-file
    path listing the batches."""
    import os
    import pickle

    out_path = f"{data_file}_{dataset_name}_batch"
    os.makedirs(out_path, exist_ok=True)
    data, labels, file_id, names = [], [], 0, []
    with tarfile.open(data_file, mode="r") as f:
        for mem in f.getmembers():
            if mem.name not in img2label:
                continue
            data.append(f.extractfile(mem).read())
            labels.append(img2label[mem.name])
            if len(data) == num_per_batch:
                output = {"label": labels, "data": data}
                name = os.path.join(out_path, f"batch_{file_id:05d}")
                with open(name, "wb") as fo:
                    pickle.dump(output, fo, protocol=2)
                file_id += 1
                names.append(name)
                data, labels = [], []
    if data:
        output = {"label": labels, "data": data}
        name = os.path.join(out_path, f"batch_{file_id:05d}")
        with open(name, "wb") as fo:
            pickle.dump(output, fo, protocol=2)
        names.append(name)
    meta = os.path.join(out_path, "batches.meta")
    with open(meta, "w") as fo:
        fo.write("\n".join(names))
    return meta
