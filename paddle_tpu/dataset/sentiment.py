"""NLTK movie-review sentiment readers (reference:
python/paddle/dataset/sentiment.py). Samples: (word_id_list, label in {0,1});
reference quirk preserved: train()/test() return generators directly, not
reader creators (:115-128). Synthetic corpus keyed by class-specific word
distributions so classifiers can actually learn."""

from __future__ import annotations

import numpy as np

WORD_DICT_LEN = 300
NUM_TRAINING_INSTANCES = 1600
NUM_TOTAL_INSTANCES = 2000


def get_word_dict():
    """word -> id, sorted by (synthetic) frequency (reference :53)."""
    return {f"word{i}": i for i in range(WORD_DICT_LEN)}


def _samples(lo, hi):
    rng = np.random.RandomState(42)
    for i in range(NUM_TOTAL_INSTANCES):
        label = i % 2
        n = int(rng.randint(5, 40))
        # polarity signal: each class draws from a shifted word range
        base = 10 if label == 0 else 150
        words = rng.randint(base, base + 120, size=n).tolist()
        if lo <= i < hi:
            yield words, label


def train():
    return _samples(0, NUM_TRAINING_INSTANCES)


def test():
    return _samples(NUM_TRAINING_INSTANCES, NUM_TOTAL_INSTANCES)
