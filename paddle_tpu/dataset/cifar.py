"""CIFAR-10/100 readers (reference: python/paddle/dataset/cifar.py).
Samples: (image[3072] float32 in [0,1], label int)."""

from __future__ import annotations

import numpy as np


def _synthetic(n, seed, classes):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, classes, n)
    for i in range(n):
        c = int(labels[i])
        img = 0.1 * rng.rand(3, 32, 32).astype(np.float32)
        img[c % 3, (c * 3) % 28:(c * 3) % 28 + 4, :] += 0.8
        yield np.clip(img, 0, 1).reshape(-1), c


def train10():
    return lambda: _synthetic(4096, 0, 10)


def test10():
    return lambda: _synthetic(512, 1, 10)


def train100():
    return lambda: _synthetic(4096, 0, 100)


def test100():
    return lambda: _synthetic(512, 1, 100)
