"""MovieLens recommender readers (reference:
python/paddle/dataset/movielens.py). Samples:
(user_id, gender, age, job, movie_id, category_ids, title_ids, rating)."""

from __future__ import annotations

import numpy as np

MAX_USER = 6040
MAX_MOVIE = 3952
CATEGORIES = 18
AGES = 7
JOBS = 21
TITLE_DICT = 5174


def max_user_id():
    return MAX_USER


def max_movie_id():
    return MAX_MOVIE


def max_job_id():
    return JOBS - 1


def age_table():
    return [1, 18, 25, 35, 45, 50, 56]


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        user = int(rng.randint(1, MAX_USER + 1))
        gender = int(rng.randint(0, 2))
        age = int(rng.randint(0, AGES))
        job = int(rng.randint(0, JOBS))
        movie = int(rng.randint(1, MAX_MOVIE + 1))
        cats = rng.randint(0, CATEGORIES, rng.randint(1, 4)).tolist()
        title = rng.randint(0, TITLE_DICT, rng.randint(1, 6)).tolist()
        # structured rating: users & movies have latent quality
        rating = float(np.clip(((user % 5) + (movie % 5)) / 2.0 + rng.randn() * 0.3,
                               0, 5))
        yield user, gender, age, job, movie, cats, title, rating


def train():
    return lambda: _synthetic(8192, 0)


def test():
    return lambda: _synthetic(1024, 1)
