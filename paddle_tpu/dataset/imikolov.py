"""PTB-style n-gram LM readers (reference: python/paddle/dataset/imikolov.py,
the word2vec book-test corpus). Samples: n-gram tuples of word ids."""

from __future__ import annotations

import numpy as np


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(2074)}


def _synthetic(n, seed, vocab, ngram):
    """Markov-chain surrogate: next word = (sum of context) % vocab + noise,
    so an embedding model has structure to learn."""
    rng = np.random.RandomState(seed)
    for _ in range(n):
        ctx = rng.randint(0, vocab, ngram - 1)
        nxt = (ctx.sum() + rng.randint(0, 3)) % vocab
        yield tuple(int(c) for c in ctx) + (int(nxt),)


def train(word_idx, n):
    return lambda: _synthetic(8192, 0, len(word_idx), n)


def test(word_idx, n):
    return lambda: _synthetic(1024, 1, len(word_idx), n)
