"""Datasets (reference: python/paddle/dataset/ — mnist, cifar, imdb,
imikolov, movielens, conll05, sentiment, uci_housing, wmt14, wmt16, ...).

Each module exposes `train()`/`test()` reader factories like the reference.
Downloads go to ~/.cache/paddle_tpu/dataset; in zero-egress environments
every dataset falls back to a deterministic synthetic surrogate with the
same sample schema, so pipelines and tests stay runnable."""

from . import common  # noqa: F401
from . import image  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
from . import wmt16  # noqa: F401
from . import imikolov  # noqa: F401
from . import movielens  # noqa: F401
from . import conll05  # noqa: F401
from . import sentiment  # noqa: F401
from . import wmt14  # noqa: F401
from . import voc2012  # noqa: F401
from . import flowers  # noqa: F401
from . import mq2007  # noqa: F401
