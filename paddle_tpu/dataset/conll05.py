"""CoNLL-2005 semantic-role-labeling readers (reference:
python/paddle/dataset/conll05.py). Each sample is nine aligned sequences:
(word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, predicate, mark, label) —
reference reader_creator :146-198.

Zero-egress environments get a synthetic corpus with the same structure:
sentences of random words, one predicate position per sentence, context
windows/marks derived exactly as the reference derives them (:155-183),
and B-V/I-A style labels.
"""

from __future__ import annotations

import os

import numpy as np

from . import common

WORD_DICT_LEN = 500
LABEL_DICT_LEN = 12
PRED_DICT_LEN = 40
UNK_IDX = 0
EMB_DIM = 32


def get_dict():
    """(word_dict, verb_dict, label_dict) — reference :201."""
    word_dict = {f"w{i}": i for i in range(WORD_DICT_LEN)}
    verb_dict = {f"v{i}": i for i in range(PRED_DICT_LEN)}
    label_dict = {f"L{i}": i for i in range(LABEL_DICT_LEN)}
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Path to a pretrained-embedding array (reference :214 returns the
    downloaded file); synthetic fallback writes a deterministic npy."""
    path = os.path.join(common.DATA_HOME, "conll05st", "emb.npy")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if not os.path.exists(path):
        rng = np.random.RandomState(0)
        np.save(path, rng.uniform(-1, 1, (WORD_DICT_LEN, EMB_DIM))
                .astype(np.float32))
    return path


def _synthetic_reader(n_samples, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_samples):
            sen_len = int(rng.randint(4, 12))
            words = rng.randint(1, WORD_DICT_LEN, size=sen_len)
            verb_index = int(rng.randint(0, sen_len))
            pred = int(rng.randint(0, PRED_DICT_LEN))
            labels = rng.randint(1, LABEL_DICT_LEN, size=sen_len)

            mark = [0] * sen_len
            mark[verb_index] = 1
            ctx_n1 = int(words[verb_index - 1]) if verb_index > 0 else UNK_IDX
            if verb_index > 0:
                mark[verb_index - 1] = 1
            ctx_n2 = int(words[verb_index - 2]) if verb_index > 1 else UNK_IDX
            if verb_index > 1:
                mark[verb_index - 2] = 1
            ctx_0 = int(words[verb_index])
            ctx_p1 = (int(words[verb_index + 1])
                      if verb_index < sen_len - 1 else UNK_IDX)
            if verb_index < sen_len - 1:
                mark[verb_index + 1] = 1
            ctx_p2 = (int(words[verb_index + 2])
                      if verb_index < sen_len - 2 else UNK_IDX)
            if verb_index < sen_len - 2:
                mark[verb_index + 2] = 1

            yield (list(words), [ctx_n2] * sen_len, [ctx_n1] * sen_len,
                   [ctx_0] * sen_len, [ctx_p1] * sen_len, [ctx_p2] * sen_len,
                   [pred] * sen_len, mark, list(labels))

    return reader


def test():
    """Reference :221 (the free split; used for training in the book)."""
    return _synthetic_reader(200, seed=1)


def train():
    return _synthetic_reader(800, seed=0)
