"""MQ2007 learning-to-rank readers (reference:
python/paddle/dataset/mq2007.py). Formats mirror the reference generators:
  pairwise (:186): yields (label[1], left_feature[46], right_feature[46])
                   where left ranks above right;
  listwise (:229): yields (relevance[n,1], features[n,46]) per query;
  pointwise (:167): yields (feature[46], relevance[1]).
Synthetic fallback: relevance is a noisy linear function of the features,
so ranking models have signal to learn."""

from __future__ import annotations

import numpy as np

FEATURE_DIM = 46


def _queries(n_queries, seed):
    rng = np.random.RandomState(seed)
    w = rng.randn(FEATURE_DIM)
    for _ in range(n_queries):
        n_docs = int(rng.randint(5, 15))
        feats = rng.randn(n_docs, FEATURE_DIM).astype(np.float32)
        score = feats @ w + 0.1 * rng.randn(n_docs)
        rel = np.digitize(score, np.percentile(score, [33, 66]))
        yield rel.astype(np.float32), feats


def __reader__(n_queries, seed, format="pairwise"):
    def pointwise():
        for rel, feats in _queries(n_queries, seed):
            for r, f in zip(rel, feats):
                yield f, np.array([r], np.float32)

    def pairwise():
        for rel, feats in _queries(n_queries, seed):
            n = len(rel)
            for i in range(n):
                for j in range(i + 1, n):
                    if rel[i] > rel[j]:
                        yield np.array([1.0], np.float32), feats[i], feats[j]
                    elif rel[i] < rel[j]:
                        yield np.array([1.0], np.float32), feats[j], feats[i]

    def listwise():
        for rel, feats in _queries(n_queries, seed):
            yield rel.reshape(-1, 1), feats

    return {"pointwise": pointwise, "pairwise": pairwise,
            "listwise": listwise}[format]


def train(format="pairwise"):
    return __reader__(40, seed=0, format=format)


def test(format="pairwise"):
    return __reader__(10, seed=1, format=format)
