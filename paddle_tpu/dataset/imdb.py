"""IMDB sentiment readers (reference: python/paddle/dataset/imdb.py).
Samples: (word_id_sequence, label in {0,1})."""

from __future__ import annotations

import numpy as np

from . import common


def word_dict(vocab_size=5147):
    return {f"w{i}": i for i in range(vocab_size)}


def _synthetic(n, seed, vocab=5147):
    """Learnable surrogate: positive samples draw from the upper half of the
    vocab, negative from the lower — a linear classifier can separate."""
    rng = np.random.RandomState(seed)
    for _ in range(n):
        label = int(rng.randint(0, 2))
        length = int(rng.randint(8, 64))
        if label:
            ids = rng.randint(vocab // 2, vocab, length)
        else:
            ids = rng.randint(0, vocab // 2, length)
        yield ids.astype(np.int64).tolist(), label


def train(word_idx=None):
    vocab = len(word_idx) if word_idx else 5147

    def reader():
        yield from _synthetic(2048, 0, vocab)

    return reader


def test(word_idx=None):
    vocab = len(word_idx) if word_idx else 5147

    def reader():
        yield from _synthetic(512, 1, vocab)

    return reader
