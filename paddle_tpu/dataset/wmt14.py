"""WMT14 en->fr translation readers (reference:
python/paddle/dataset/wmt14.py). Samples: (src_ids, trg_ids, trg_ids_next)
with <s>/<e>/<unk> conventions (reference reader_creator :78-110: src gets
START+words+END, trg gets START+words, trg_next gets words+END).

Synthetic fallback: "translation" pairs where the target is a deterministic
permutation of the source sequence, so seq2seq models can fit it."""

from __future__ import annotations

import numpy as np

START = "<s>"
END = "<e>"
UNK = "<unk>"
START_ID, END_ID, UNK_IDX = 0, 1, 2


def get_dict(dict_size, reverse=True):
    """(src_dict, trg_dict); reverse=True gives id->word (reference :151)."""
    words = [START, END, UNK] + [f"w{i}" for i in range(dict_size - 3)]
    d = {w: i for i, w in enumerate(words)}
    if reverse:
        d = {i: w for w, i in d.items()}
    return d, dict(d)


def _reader(dict_size, n_samples, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_samples):
            n = int(rng.randint(3, 12))
            src = rng.randint(3, dict_size, size=n).tolist()
            trg = [int(dict_size - 1 - (w - 3) % (dict_size - 3))
                   for w in src]  # deterministic mapping
            src_ids = [START_ID] + src + [END_ID]
            trg_ids = [START_ID] + trg
            trg_ids_next = trg + [END_ID]
            yield src_ids, trg_ids, trg_ids_next

    return reader


def train(dict_size):
    return _reader(dict_size, 1000, seed=0)


def test(dict_size):
    return _reader(dict_size, 100, seed=1)


def gen(dict_size):
    return _reader(dict_size, 100, seed=2)
