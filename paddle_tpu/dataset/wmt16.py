"""WMT'16 En-De NMT readers (reference: python/paddle/dataset/wmt16.py).
Samples: (src_ids, trg_ids, trg_next_ids) with <s>/<e>/<unk> conventions."""

from __future__ import annotations

import numpy as np

START_ID, END_ID, UNK_ID = 0, 1, 2


def _synthetic(n, seed, src_vocab, trg_vocab):
    """Copy-task surrogate: target is source mapped into the trg vocab —
    a real seq2seq learning signal without the corpus."""
    rng = np.random.RandomState(seed)
    for _ in range(n):
        length = int(rng.randint(4, 24))
        src = rng.randint(3, src_vocab, length)
        trg = (src % (trg_vocab - 3)) + 3
        trg_in = np.concatenate([[START_ID], trg])
        trg_next = np.concatenate([trg, [END_ID]])
        yield (src.astype(np.int64).tolist(),
               trg_in.astype(np.int64).tolist(),
               trg_next.astype(np.int64).tolist())


def train(src_dict_size=30000, trg_dict_size=30000):
    def reader():
        yield from _synthetic(4096, 0, src_dict_size, trg_dict_size)

    return reader


def test(src_dict_size=30000, trg_dict_size=30000):
    def reader():
        yield from _synthetic(512, 1, src_dict_size, trg_dict_size)

    return reader


def get_dict(lang, dict_size, reverse=False):
    d = {f"{lang}{i}": i for i in range(dict_size)}
    return {v: k for k, v in d.items()} if reverse else d
