"""MNIST readers (reference: python/paddle/dataset/mnist.py).

train()/test() yield (image[784] float32 in [-1,1], label int) like the
reference. Real download when permitted; deterministic synthetic digits
otherwise (zero-egress default)."""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from . import common

URL_PREFIX = "https://ossci-datasets.s3.amazonaws.com/mnist/"
TRAIN_IMAGE = "train-images-idx3-ubyte.gz"
TRAIN_LABEL = "train-labels-idx1-ubyte.gz"
TEST_IMAGE = "t10k-images-idx3-ubyte.gz"
TEST_LABEL = "t10k-labels-idx1-ubyte.gz"


def _parse(image_path, label_path):
    with gzip.open(label_path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        labels = np.frombuffer(f.read(), np.uint8)
    with gzip.open(image_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        images = np.frombuffer(f.read(), np.uint8).reshape(n, rows * cols)
    images = images.astype(np.float32) / 127.5 - 1.0
    return images, labels.astype(np.int64)


def _synthetic(n, seed):
    """Deterministic learnable surrogate: class-dependent bright blob."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n).astype(np.int64)
    images = 0.1 * rng.randn(n, 784).astype(np.float32)
    img = images.reshape(n, 28, 28)
    for i in range(n):
        c = int(labels[i])
        img[i, 2 * c: 2 * c + 4, 2 * c: 2 * c + 4] += 1.5
    return np.clip(images, -1, 1), labels


def _reader(image_name, label_name, synth_n, seed):
    def reader():
        if common.can_download():
            try:
                ip = common.download(URL_PREFIX + image_name, "mnist", None)
                lp = common.download(URL_PREFIX + label_name, "mnist", None)
                images, labels = _parse(ip, lp)
            except RuntimeError:
                images, labels = _synthetic(synth_n, seed)
        else:
            images, labels = _synthetic(synth_n, seed)
        for x, y in zip(images, labels):
            yield x, int(y)

    return reader


def train():
    return _reader(TRAIN_IMAGE, TRAIN_LABEL, 8192, 0)


def test():
    return _reader(TEST_IMAGE, TEST_LABEL, 1024, 1)
