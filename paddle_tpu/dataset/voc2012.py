"""PASCAL VOC2012 segmentation readers (reference:
python/paddle/dataset/voc2012.py). Samples: (image f32 [3,H,W] in [0,1],
label mask int32 [H,W] with 21 classes). Synthetic fallback: images with a
colored rectangle whose mask is the ground truth."""

from __future__ import annotations

import numpy as np

N_CLASSES = 21
H = W = 32  # synthetic resolution (reference images are full-size JPEG)


def _reader(n_samples, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n_samples):
            img = rng.rand(3, H, W).astype(np.float32) * 0.2
            mask = np.zeros((H, W), np.int32)
            cls = int(rng.randint(1, N_CLASSES))
            y0, x0 = rng.randint(0, H // 2, size=2)
            h, w = rng.randint(4, H // 2, size=2)
            img[:, y0:y0 + h, x0:x0 + w] += cls / N_CLASSES
            mask[y0:y0 + h, x0:x0 + w] = cls
            yield img, mask

    return reader


def train():
    return _reader(120, seed=0)


def test():
    return _reader(30, seed=1)


def val():
    return _reader(30, seed=2)
