"""Thread-local default-scope stack (reference:
python/paddle/fluid/default_scope_funcs.py). The reference kept a stack of
C++ scopes for SWIG-era code; here the stack holds core Scope objects over
the same global root used by the Executor."""

from __future__ import annotations

import threading

from .core.executor import Scope, global_scope

__all__ = [
    "get_cur_scope",
    "enter_local_scope",
    "leave_local_scope",
    "var",
    "find_var",
    "scoped_function",
]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack") or not _tls.stack:
        _tls.stack = [global_scope()]
    return _tls.stack


def get_cur_scope() -> Scope:
    """Innermost scope (reference default_scope_funcs.py get_cur_scope)."""
    return _stack()[-1]


def enter_local_scope():
    cur = get_cur_scope()
    _stack().append(cur.new_scope())


def leave_local_scope():
    _stack().pop()
    get_cur_scope().drop_kids()


def var(name: str):
    """Get-or-create a variable slot in the current scope (the reference's
    Scope::Var). Creates an uninitialized (None) entry when absent."""
    scope = get_cur_scope()
    if scope.var(name) is None and not scope.has_var(name):
        scope.set_var(name, None)
    return scope.var(name)


def find_var(name: str):
    return get_cur_scope().find_var(name)


def scoped_function(func):
    """Run `func` inside a fresh local scope (reference scoped_function)."""
    enter_local_scope()
    try:
        return func()
    finally:
        leave_local_scope()
